"""``step-purity`` — handler effects flow only through the returned Step.

The deterministic core contract (``core/step.py``): a
``DistAlgorithm.handle_*`` method may mutate its *own* state (``self``)
and must report every observable effect — outputs, outgoing messages,
fault attributions — in the :class:`Step` it returns.  The caller
delivers messages; the handler never touches a transport, never writes
caller-visible state, and never mutates its arguments (incoming
messages are shared between the router and other recipients in the
simulated network — an in-place edit corrupts peers).

This is a dataflow pass, scoped to classes whose AST bases name
``DistAlgorithm``: ``SyncKeyGen`` and other helper classes with
out-parameter conventions are deliberately out of scope.  Inside each
``handle_*`` method it flags:

- mutation of a parameter (attribute/subscript stores, ``del``,
  augmented assigns, or known mutator-method calls rooted at a
  parameter or a local aliasing one);
- writes to module-level state (``global``/``nonlocal`` declarations,
  stores rooted at a module-level binding);
- direct transport / IO calls (names imported from
  ``hbbft_tpu.transport``, ``socket`` methods, ``print``/``open``);
- returns that are not step-shaped: every explicit ``return`` must
  produce a Step (constructor/classmethod, a Step-classified local, a
  ``self._helper(...)`` result, or a combinator chain on one) and a
  bare ``return``/``return None`` drops the implicit empty Step.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set

from ..core import FileContext, Rule, Violation
from ._ast_util import dotted_name

# In-place mutators on containers and Steps.  Calling one of these on
# an argument-derived value leaks effects outside the returned Step.
_MUTATORS = frozenset(
    {
        "append",
        "extend",
        "add",
        "update",
        "insert",
        "setdefault",
        "pop",
        "popitem",
        "remove",
        "discard",
        "clear",
        "sort",
        "reverse",
        "merge",
        # Step combinators — fine on a fresh Step, not on a caller's.
        "extend_with",
        "add_fault",
        "send_all",
        "send_to",
    }
)

_TRANSPORT_CALLS = frozenset(
    {"send", "sendall", "sendto", "recv", "recvfrom", "connect", "bind", "listen", "accept"}
)


def _base_names(cls: ast.ClassDef) -> Set[str]:
    out: Set[str] = set()
    for b in cls.bases:
        name = dotted_name(b)
        if name:
            out.add(name.rsplit(".", 1)[-1])
    return out


def _root_name(node: ast.AST) -> Optional[str]:
    """The leftmost Name of an Attribute/Subscript/Name chain."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


def _module_level_names(tree: ast.Module) -> Set[str]:
    """Names bound at module top level (mutable-state write targets)."""
    names: Set[str] = set()
    for stmt in tree.body:
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
            for t in targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
    return names


def _transport_imports(tree: ast.Module) -> Set[str]:
    """Local names bound by importing from the transport layer (or the
    socket module itself)."""
    names: Set[str] = set()
    for stmt in ast.walk(tree):
        if isinstance(stmt, ast.ImportFrom):
            mod = stmt.module or ""
            if "transport" in mod.split(".") or mod == "socket":
                for a in stmt.names:
                    names.add(a.asname or a.name)
        elif isinstance(stmt, ast.Import):
            for a in stmt.names:
                if a.name == "socket" or "transport" in a.name.split("."):
                    names.add((a.asname or a.name).split(".")[0])
    return names


class StepPurityRule(Rule):
    name = "step-purity"
    description = (
        "DistAlgorithm handle_* effects flow only through the returned "
        "Step: no argument mutation, module-state writes, transport "
        "calls, or non-Step returns"
    )
    scope = ("protocols/",)

    def check(self, ctx: FileContext) -> Iterable[Violation]:
        module_names = _module_level_names(ctx.tree)
        transport_names = _transport_imports(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            if "DistAlgorithm" not in _base_names(node):
                continue
            for item in node.body:
                if isinstance(item, ast.FunctionDef) and item.name.startswith(
                    "handle_"
                ):
                    yield from self._check_handler(
                        ctx, item, module_names, transport_names
                    )

    # -- one handler -------------------------------------------------------

    def _check_handler(
        self,
        ctx: FileContext,
        fn: ast.FunctionDef,
        module_names: Set[str],
        transport_names: Set[str],
    ) -> Iterable[Violation]:
        params = {a.arg for a in fn.args.args if a.arg != "self"}
        params.update(a.arg for a in fn.args.kwonlyargs)
        if fn.args.vararg:
            params.add(fn.args.vararg.arg)
        if fn.args.kwarg:
            params.add(fn.args.kwarg.arg)

        tainted, step_like = self._classify_locals(fn, params)

        def is_tainted(root: Optional[str]) -> bool:
            return root is not None and (root in params or root in tainted)

        for sub in ast.walk(fn):
            # (a) global / nonlocal escape hatches
            if isinstance(sub, (ast.Global, ast.Nonlocal)):
                kw = "global" if isinstance(sub, ast.Global) else "nonlocal"
                yield self.violation(
                    ctx,
                    sub,
                    f"{fn.name} declares '{kw} {', '.join(sub.names)}' — "
                    "handler effects must flow through the returned Step",
                )
                continue

            # (b) stores through attributes/subscripts of non-self roots
            if isinstance(sub, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (
                    sub.targets if isinstance(sub, ast.Assign) else [sub.target]
                )
                for t in targets:
                    yield from self._check_store(
                        ctx, fn, t, is_tainted, module_names
                    )
            elif isinstance(sub, ast.Delete):
                for t in sub.targets:
                    yield from self._check_store(
                        ctx, fn, t, is_tainted, module_names, verb="deletes"
                    )

            # (c) mutator-method calls on tainted roots; transport calls
            elif isinstance(sub, ast.Call):
                yield from self._check_call(
                    ctx, fn, sub, is_tainted, transport_names
                )

        # (d) every explicit return is step-shaped
        for ret in ast.walk(fn):
            if isinstance(ret, ast.Return) and self._in_function(fn, ret):
                yield from self._check_return(ctx, fn, ret, step_like)

    @staticmethod
    def _in_function(fn: ast.FunctionDef, node: ast.AST) -> bool:
        """Exclude returns belonging to nested defs/lambdas."""
        for sub in ast.walk(fn):
            if sub is fn:
                continue
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                for inner in ast.walk(sub):
                    if inner is node:
                        return False
        return True

    def _classify_locals(
        self, fn: ast.FunctionDef, params: Set[str]
    ) -> "tuple[Set[str], Set[str]]":
        """→ (tainted locals aliasing a parameter, Step-classified
        locals).  Flow-insensitive single pass in line order: a name
        assigned from a bare param chain is tainted; one assigned from
        a Step constructor, a ``self`` method call, or a call on an
        existing Step local is step-like.  Call results are fresh —
        ``list(msg.votes)`` copies."""
        tainted: Set[str] = set()
        step_like: Set[str] = set()
        assigns = sorted(
            (n for n in ast.walk(fn) if isinstance(n, (ast.Assign, ast.AnnAssign))),
            key=lambda n: n.lineno,
        )
        for a in assigns:
            value = a.value
            if value is None:  # bare annotation
                continue
            targets = a.targets if isinstance(a, ast.Assign) else [a.target]
            names = [t.id for t in targets if isinstance(t, ast.Name)]
            # tuple targets: taint conservatively from a param chain RHS
            for t in targets:
                if isinstance(t, (ast.Tuple, ast.List)):
                    names.extend(
                        e.id for e in t.elts if isinstance(e, ast.Name)
                    )
            if not names:
                continue
            if self._is_step_expr(value, step_like):
                step_like.update(names)
            elif not isinstance(value, ast.Call):
                root = _root_name(value)
                if root is not None and (root in params or root in tainted):
                    tainted.update(names)
                elif root in step_like:
                    step_like.update(names)
        return tainted, step_like

    @staticmethod
    def _is_step_expr(value: ast.AST, step_like: Set[str]) -> bool:
        """Step constructor / classmethod, ``self._helper(...)``, or a
        combinator call on a step-like value."""
        if not isinstance(value, ast.Call):
            return False
        name = dotted_name(value.func)
        if name is None:
            return False
        head = name.split(".", 1)[0]
        return (
            head == "Step"
            or head == "self"
            or head in step_like
        )

    def _check_store(
        self,
        ctx: FileContext,
        fn: ast.FunctionDef,
        target: ast.AST,
        is_tainted,
        module_names: Set[str],
        verb: str = "writes",
    ) -> Iterable[Violation]:
        if not isinstance(target, (ast.Attribute, ast.Subscript)):
            return
        root = _root_name(target)
        if root is None or root == "self":
            return
        if is_tainted(root):
            yield self.violation(
                ctx,
                target,
                f"{fn.name} {verb} through argument-derived '{root}' — "
                "incoming messages are shared; report effects via the "
                "returned Step",
            )
        elif root in module_names:
            yield self.violation(
                ctx,
                target,
                f"{fn.name} {verb} module-level state '{root}' — "
                "caller-visible state outside self breaks replay "
                "determinism",
            )

    def _check_call(
        self,
        ctx: FileContext,
        fn: ast.FunctionDef,
        call: ast.Call,
        is_tainted,
        transport_names: Set[str],
    ) -> Iterable[Violation]:
        name = dotted_name(call.func)
        if name is None:
            return
        parts = name.split(".")
        root, leaf = parts[0], parts[-1]
        if root in transport_names or (
            len(parts) > 1 and root == "socket"
        ):
            yield self.violation(
                ctx,
                call,
                f"{fn.name} calls transport API '{name}' — handlers "
                "emit messages via the returned Step; the caller "
                "delivers them",
            )
            return
        if len(parts) == 1 and leaf in ("print", "open"):
            yield self.violation(
                ctx,
                call,
                f"{fn.name} calls {leaf}() — side-channel IO inside a "
                "deterministic handler",
            )
            return
        if len(parts) > 1 and leaf in _TRANSPORT_CALLS and root != "self":
            yield self.violation(
                ctx,
                call,
                f"{fn.name} calls socket-style '{name}' — handlers "
                "never touch a transport; the caller delivers Step "
                "messages",
            )
            return
        if len(parts) > 1 and leaf in _MUTATORS and is_tainted(root):
            yield self.violation(
                ctx,
                call,
                f"{fn.name} mutates argument-derived '{root}' via "
                f".{leaf}() — incoming messages are shared; report "
                "effects via the returned Step",
            )

    def _check_return(
        self,
        ctx: FileContext,
        fn: ast.FunctionDef,
        ret: ast.Return,
        step_like: Set[str],
    ) -> Iterable[Violation]:
        value = ret.value
        if value is None or (
            isinstance(value, ast.Constant) and value.value is None
        ):
            yield self.violation(
                ctx,
                ret,
                f"{fn.name} returns None — return an (empty) Step so "
                "the caller can deliver messages and faults",
            )
            return
        if isinstance(value, ast.Name) and value.id in step_like:
            return
        if self._is_step_expr(value, step_like):
            return
        if isinstance(value, ast.IfExp):
            yield from self._check_return(
                ctx, fn, ast.Return(value=value.body, lineno=ret.lineno, col_offset=ret.col_offset), step_like
            )
            yield from self._check_return(
                ctx, fn, ast.Return(value=value.orelse, lineno=ret.lineno, col_offset=ret.col_offset), step_like
            )
            return
        yield self.violation(
            ctx,
            ret,
            f"{fn.name} returns a non-Step value — handler results "
            "flow through Step.output, not the return slot",
        )
