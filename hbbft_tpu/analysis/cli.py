"""The badgerlint CLI.

::

    python -m hbbft_tpu.analysis [paths...]          # human output
    python -m hbbft_tpu.analysis --json [paths...]   # CI / pre-commit
    python -m hbbft_tpu.analysis --format sarif      # PR annotations
    python -m hbbft_tpu.analysis --write-baseline    # re-baseline (reviewed!)
    python -m hbbft_tpu.analysis --write-wire-manifest  # pin @wire registry
    python -m hbbft_tpu.analysis --write-range-manifest # pin limbprove peaks
    python -m hbbft_tpu.analysis --racecheck tests/test_racecheck.py
                                  # runtime lockset checker over pytest
    python -m hbbft_tpu.analysis --stallcheck tests/test_stallcheck.py
                                  # event-loop stall sanitizer over pytest
    python -m hbbft_tpu.analysis --rangecheck tests/test_fused_flush.py
                                  # exact-shadow overflow sanitizer over pytest
    python -m hbbft_tpu.analysis --mc --mc-config agreement --mc-depth 5
                                  # badgermc: schedule-space model checking

Exit codes: 0 clean (baselined violations allowed), 1 new violations
or parse errors, 2 usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List, Optional

from .core import Baseline, Violation, lint_paths
from .rules import all_rules
from .rules.wire_stability import DEFAULT_MANIFEST, build_manifest, write_manifest

_HERE = os.path.dirname(os.path.abspath(__file__))
DEFAULT_BASELINE = os.path.join(_HERE, "baseline.json")


def _default_paths() -> List[str]:
    """The hbbft_tpu package directory itself."""
    return [os.path.dirname(_HERE)]


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m hbbft_tpu.analysis",
        description="badgerlint — AST invariant checks for hbbft_tpu",
    )
    parser.add_argument(
        "paths", nargs="*", help="files/dirs to lint (default: the package)"
    )
    parser.add_argument("--json", action="store_true", help="machine output")
    parser.add_argument(
        "--format",
        choices=("human", "json", "sarif"),
        default=None,
        help="output format (--json is shorthand for --format json)",
    )
    parser.add_argument(
        "--manifest",
        default=DEFAULT_MANIFEST,
        help="wire manifest file (default: the checked-in one)",
    )
    parser.add_argument(
        "--write-wire-manifest",
        action="store_true",
        help="regenerate the @wire golden manifest from the scanned "
        "paths and exit 0",
    )
    parser.add_argument(
        "--write-range-manifest",
        action="store_true",
        help="re-verify every registered kernel with limbprove "
        "(analysis.rangecheck) and pin the proof-obligation peaks to "
        "range_manifest.json, then exit 0",
    )
    parser.add_argument(
        "--baseline",
        default=DEFAULT_BASELINE,
        help="baseline file (default: the checked-in one)",
    )
    parser.add_argument(
        "--no-baseline",
        action="store_true",
        help="report baselined violations as failures too",
    )
    parser.add_argument(
        "--write-baseline",
        metavar="JUSTIFICATION",
        help="write every current violation to the baseline file with "
        "this justification and exit 0",
    )
    parser.add_argument(
        "--select",
        help="comma-separated rule names to run (default: all)",
    )
    parser.add_argument(
        "--changed",
        action="store_true",
        help="lint only python files in the git diff (staged + "
        "unstaged) — but widen to a full run whenever a changed file "
        "is in a whole-project rule's domain, because scoping an "
        "interprocedural rule to the diff silently under-reports",
    )
    parser.add_argument(
        "--trace",
        metavar="PATH",
        help="write a JSONL obs trace with a lint_run event for this run",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="list rules and exit"
    )
    parser.add_argument(
        "--racecheck",
        metavar="TEST_EXPR",
        help="run `pytest --racecheck TEST_EXPR` in a subprocess under "
        "the Eraser-style runtime lockset checker "
        "(hbbft_tpu.analysis.racecheck) and render its candidate races "
        "like lint violations",
    )
    parser.add_argument(
        "--stallcheck",
        metavar="TEST_EXPR",
        help="run `pytest --stallcheck TEST_EXPR` in a subprocess under "
        "the event-loop stall sanitizer (hbbft_tpu.analysis.stallcheck) "
        "and render its stall reports like lint violations",
    )
    parser.add_argument(
        "--rangecheck",
        metavar="TEST_EXPR",
        help="run `pytest --rangecheck TEST_EXPR` in a subprocess under "
        "the arbitrary-precision shadow sanitizer "
        "(hbbft_tpu.analysis.rangeshadow) and render its overflow "
        "witnesses like lint violations",
    )
    parser.add_argument(
        "--stall-budget",
        type=float,
        default=None,
        metavar="SECONDS",
        help="stallcheck budget in seconds (default: "
        "$HBBFT_TPU_STALLCHECK_BUDGET or 0.25)",
    )
    parser.add_argument(
        "--mc",
        action="store_true",
        help="run badgermc (hbbft_tpu.analysis.modelcheck): bounded "
        "schedule-space model checking of the protocol state machines, "
        "rendering any violated invariant like a lint violation with "
        "the minimized counterexample trace as its flow",
    )
    parser.add_argument(
        "--mc-config",
        default="honey_badger",
        metavar="PROTOCOL",
        help="protocol stack to check (honey_badger, common_subset, "
        "agreement, sbv_broadcast, common_coin; default honey_badger)",
    )
    parser.add_argument(
        "--mc-depth", type=int, default=None, help="DFS delivery-depth bound"
    )
    parser.add_argument(
        "--mc-states",
        type=int,
        default=None,
        help="explored-state cap (the run reports truncated=True when hit)",
    )
    parser.add_argument(
        "--mc-corrupt",
        type=int,
        default=None,
        help="number of Byzantine nodes (highest ids; enables "
        "drop/dup/forge choice points)",
    )
    parser.add_argument(
        "--mc-seed", type=int, default=None, help="exploration seed"
    )
    parser.add_argument(
        "--mc-epochs", type=int, default=None, help="honey_badger epochs"
    )
    parser.add_argument(
        "--mc-reveal",
        choices=("inline", "ordered"),
        default=None,
        help="honey_badger reveal mode",
    )
    parser.add_argument(
        "--mc-probes",
        type=int,
        default=None,
        help="full-delivery liveness/deep-safety probes (odd-indexed "
        "probes bias against a random partition cut)",
    )
    parser.add_argument(
        "--mc-probe-steps",
        type=int,
        default=None,
        help="per-probe delivery bound",
    )
    parser.add_argument(
        "--mc-prefix",
        type=int,
        default=None,
        help="seeded random warm-up deliveries before the DFS (reaches "
        "deeper protocol phases at the cost of exhaustiveness)",
    )
    parser.add_argument(
        "--mc-byz-budget",
        type=int,
        default=None,
        help="adversarial actions allowed per explored schedule",
    )
    parser.add_argument(
        "--mc-repro",
        metavar="PATH",
        default=None,
        help="write a replayable counterexample file here on violation "
        "(replay: python -m hbbft_tpu.harness.scenarios --replay-trace PATH)",
    )
    parser.add_argument(
        "--mc-min-states",
        type=int,
        default=0,
        metavar="N",
        help="fail the run if fewer than N states were explored (guards "
        "the CI smoke against a silently degenerate search)",
    )
    args = parser.parse_args(argv)
    fmt = args.format or ("json" if args.json else "human")

    if args.mc:
        return _run_mc(args, fmt)
    if args.racecheck is not None:
        return _run_racecheck(args.racecheck, fmt)
    if args.stallcheck is not None:
        return _run_stallcheck(args.stallcheck, fmt, args.stall_budget)
    if args.rangecheck is not None:
        return _run_rangecheck(args.rangecheck, fmt)

    if args.write_range_manifest:
        from . import rangecheck as _rk

        result = _rk.verify_all()
        path = _rk.write_manifest()
        obligations = [o for r in result.reports for o in r.obligations]
        print(
            f"wrote {len(obligations)} obligation(s) "
            f"({sum(1 for o in obligations if o.proved)} proved) to {path}"
        )
        if args.trace:
            _emit_range_event(args.trace, result)
        return 0 if result.proved else 1

    rules = all_rules()
    if args.list_rules:
        for r in rules:
            print(f"{r.name:14s} {r.description}")
        return 0
    for r in rules:
        if r.name == "wire-stability":
            r.manifest_path = args.manifest
    if args.select:
        wanted = {s.strip() for s in args.select.split(",")}
        unknown = wanted - {r.name for r in rules}
        if unknown:
            print(f"unknown rule(s): {', '.join(sorted(unknown))}", file=sys.stderr)
            return 2
        rules = [r for r in rules if r.name in wanted]

    paths = args.paths or _default_paths()
    if args.changed:
        changed = _git_changed_files()
        if not changed:
            print("lint: no changed python files")
            return 0
        widening = _widening_rules(changed, rules)
        if widening:
            print(
                "lint: changed file(s) in the domain of whole-project "
                f"rule(s) [{', '.join(sorted(widening))}] — widening to "
                "a full run",
                file=sys.stderr,
            )
        else:
            paths = changed
    for p in paths:
        if not os.path.exists(p):
            print(f"no such path: {p}", file=sys.stderr)
            return 2

    if args.write_wire_manifest:
        manifest = build_manifest(paths)
        write_manifest(manifest, args.manifest)
        print(
            f"wrote {len(manifest['types'])} wire type(s) and "
            f"{len(manifest['primitive_tags'])} primitive tag(s) to "
            f"{args.manifest}"
        )
        return 0

    t0 = time.perf_counter()
    violations, errors = lint_paths(paths, rules)
    wall = time.perf_counter() - t0

    if args.write_baseline is not None:
        bl = Baseline.from_violations(violations, args.write_baseline)
        bl.save(args.baseline)
        print(
            f"wrote {len(bl.entries)} baseline entr"
            f"{'y' if len(bl.entries) == 1 else 'ies'} to {args.baseline}"
        )
        return 0

    baseline = Baseline()
    if not args.no_baseline and os.path.exists(args.baseline):
        baseline = Baseline.load(args.baseline)
    new, baselined = baseline.split(violations)

    if args.trace:
        from .. import obs

        rec = obs.enable(args.trace)
        rec.event(
            "lint_run",
            rules=len(rules),
            violations=len(new),
            wall=round(wall, 6),
            baselined=len(baselined),
            errors=len(errors),
            counts=_counts(new),
            paths=len(paths),
            changed=bool(args.changed),
        )
        _range_event_if_ran(rec)
        obs.disable()

    if fmt == "json":
        print(
            json.dumps(
                {
                    "version": 1,
                    "violations": [v.as_dict() for v in new],
                    "baselined": [v.as_dict() for v in baselined],
                    "errors": errors,
                    "counts": _counts(new),
                    "ok": not new and not errors,
                },
                indent=2,
            )
        )
    elif fmt == "sarif":
        print(json.dumps(_sarif(new, errors, rules), indent=2))
    else:
        for v in new:
            print(v.render())
            for hop_path, hop_line, note in v.flow or ():
                print(f"    flow: {hop_path}:{hop_line}: {note}")
        for e in errors:
            print(e)
        if new or errors:
            print(
                f"\n{len(new)} violation(s)"
                + (f", {len(errors)} parse error(s)" if errors else "")
                + (f" ({len(baselined)} baselined)" if baselined else "")
            )
        else:
            suffix = f" ({len(baselined)} baselined)" if baselined else ""
            print(f"clean{suffix}")
    return 1 if (new or errors) else 0


def _range_event_if_ran(rec) -> None:
    """Emit a ``range_check`` obs event when limbprove verified kernels
    during this run (the ``limb-range`` rule memoizes its RunResult)."""
    mod = sys.modules.get(__package__ + ".rangecheck")
    result = getattr(mod, "_VERIFY_CACHE", None) if mod else None
    if result is None:
        return
    rec.event(
        "range_check",
        obligations=len(result.obligations),
        proved=sum(1 for o in result.obligations if o.proved),
        wall=round(result.wall, 6),
    )


def _emit_range_event(trace_path: str, result) -> None:
    from .. import obs

    rec = obs.enable(trace_path)
    rec.event(
        "range_check",
        obligations=len(result.obligations),
        proved=sum(1 for o in result.obligations if o.proved),
        wall=round(result.wall, 6),
    )
    obs.disable()


def _git_changed_files() -> List[str]:
    """Python files in the git diff (staged + unstaged) that still
    exist on disk, repo-root-relative → absolute."""
    import subprocess

    repo_root = os.path.dirname(os.path.dirname(_HERE))
    names = set()
    for extra in ((), ("--cached",)):
        try:
            out = subprocess.run(
                ["git", "diff", "--name-only", *extra, "HEAD", "--", "*.py"],
                cwd=repo_root,
                capture_output=True,
                text=True,
                check=True,
            ).stdout
        except (OSError, subprocess.CalledProcessError):
            return []
        names.update(line.strip() for line in out.splitlines() if line.strip())
    files = []
    for name in sorted(names):
        abspath = os.path.join(repo_root, name)
        if os.path.isfile(abspath):
            files.append(abspath)
    return files


def _widening_rules(changed: List[str], rules) -> List[str]:
    """Whole-project rules whose domain contains a changed file — the
    rules for which a diff-scoped run silently under-reports."""
    from .core import PACKAGE_NAME

    widening = []
    for rule in rules:
        if not getattr(rule, "whole_project", False):
            continue
        for abspath in changed:
            norm = abspath.replace(os.sep, "/")
            marker = "/" + PACKAGE_NAME + "/"
            idx = norm.rfind(marker)
            if idx == -1:
                continue  # outside the package: in no rule's domain
            relpath = norm[idx + len(marker):]
            if not rule.scope or any(
                relpath.startswith(p) for p in rule.scope
            ):
                widening.append(rule.name)
                break
    return widening


def _run_racecheck(test_expr: str, fmt: str) -> int:
    """Drive ``pytest --racecheck`` in a subprocess (the shims must be
    installed in the process that runs the tests, and the caller's JAX
    state must stay untouched), collect the JSONL report and render the
    candidate races with the usual formatters."""
    import shlex
    import subprocess
    import tempfile

    from . import racecheck as _rc

    repo_root = os.path.dirname(os.path.dirname(_HERE))
    with tempfile.TemporaryDirectory() as td:
        out = os.path.join(td, "racecheck.jsonl")
        env = dict(os.environ)
        env[_rc.OUT_ENV] = out
        cmd = [
            sys.executable,
            "-m",
            "pytest",
            "-q",
            "-p",
            "no:cacheprovider",
            "--racecheck",
            *shlex.split(test_expr),
        ]
        proc = subprocess.run(cmd, env=env, cwd=repo_root)
        reports = _rc.load_reports(out)

    violations = [r.as_violation() for r in reports]
    if fmt == "json":
        print(
            json.dumps(
                {
                    "version": 1,
                    "violations": [v.as_dict() for v in violations],
                    "pytest_exit": proc.returncode,
                    "ok": not violations and proc.returncode == 0,
                },
                indent=2,
            )
        )
    elif fmt == "sarif":

        class _RcRule:
            name = "racecheck"
            description = (
                "runtime lockset checker: every shared-modified variable "
                "keeps a non-empty candidate lockset"
            )

        print(json.dumps(_sarif(violations, [], [_RcRule()]), indent=2))
    else:
        for v in violations:
            print(v.render())
        if violations:
            print(f"\n{len(violations)} candidate race(s)")
        else:
            print("racecheck clean")
    return 1 if (violations or proc.returncode) else 0


def _run_rangecheck(test_expr: str, fmt: str) -> int:
    """Drive ``pytest --rangecheck`` in a subprocess (the kernel shims
    must live in the process that runs the tests), collect the JSONL
    overflow witnesses and render them with the usual formatters."""
    import shlex
    import subprocess
    import tempfile

    from . import rangeshadow as _rs

    repo_root = os.path.dirname(os.path.dirname(_HERE))
    with tempfile.TemporaryDirectory() as td:
        out = os.path.join(td, "rangecheck.jsonl")
        env = dict(os.environ)
        env[_rs.OUT_ENV] = out
        cmd = [
            sys.executable,
            "-m",
            "pytest",
            "-q",
            "-p",
            "no:cacheprovider",
            "--rangecheck",
            *shlex.split(test_expr),
        ]
        proc = subprocess.run(cmd, env=env, cwd=repo_root)
        reports = _rs.load_reports(out)

    violations = [r.as_violation() for r in reports]
    if fmt == "json":
        print(
            json.dumps(
                {
                    "version": 1,
                    "violations": [v.as_dict() for v in violations],
                    "pytest_exit": proc.returncode,
                    "ok": not violations and proc.returncode == 0,
                },
                indent=2,
            )
        )
    elif fmt == "sarif":

        class _RkRule:
            name = "rangecheck"
            description = (
                "exact-shadow overflow sanitizer: sampled device kernel "
                "calls match their arbitrary-precision recomputation"
            )

        print(json.dumps(_sarif(violations, [], [_RkRule()]), indent=2))
    else:
        for v in violations:
            print(v.render())
        if violations:
            print(f"\n{len(violations)} overflow witness(es)")
        else:
            print("rangecheck clean")
    return 1 if (violations or proc.returncode) else 0


def _mc_step_label(i: int, act) -> str:
    kind = act[0]
    if kind == "forge":
        return f"step {i}: corrupt {act[1]} forges {act[3]!r} to {act[2]}"
    if kind == "drop":
        return f"step {i}: drop {act[1]}->{act[2]} (seq {act[3]})"
    if kind == "dup":
        return f"step {i}: duplicate {act[1]}->{act[2]} (seq {act[3]})"
    if kind == "reorder":
        return f"step {i}: reorder {act[1]}->{act[2]} (seq {act[3]})"
    return f"step {i}: deliver {act[1]}->{act[2]} (seq {act[3]})"


def _mc_violation(result) -> Violation:
    """Render a model-checking violation like a lint violation: anchored
    at the checked stack's source file, with the minimized
    counterexample trace as the flow (SARIF codeFlows)."""
    v = result.violation
    cfg = result.config
    path = os.path.join(
        os.path.dirname(_HERE), "protocols", f"{cfg.protocol}.py"
    )
    trace = v.get("trace", [])
    flow = tuple(
        (path, 1, _mc_step_label(i, act)) for i, act in enumerate(trace)
    )
    node = v.get("node")
    where = f" at node {node}" if node is not None else ""
    msg = (
        f"{v['kind']}{where} in {cfg.protocol} "
        f"(n={cfg.n}, corrupt={cfg.corrupt}): {v['detail']} "
        f"[counterexample: {v.get('prefix_len', 0)} prefix + "
        f"{len(trace)} shown action(s)]"
    )
    return Violation(
        rule="modelcheck",
        path=path,
        line=1,
        col=0,
        message=msg,
        flow=flow or None,
    )


def _run_mc(args, fmt: str) -> int:
    """Run badgermc in-process and render the result with the usual
    formatters."""
    from ..harness.mc_net import PROTOCOLS, MCConfig
    from .modelcheck import run_modelcheck

    if args.mc_config not in PROTOCOLS:
        print(
            f"unknown --mc-config {args.mc_config!r} "
            f"(choose from {', '.join(sorted(PROTOCOLS))})",
            file=sys.stderr,
        )
        return 2
    kw = {"protocol": args.mc_config}
    for attr, field_name in (
        ("mc_depth", "depth"),
        ("mc_states", "max_states"),
        ("mc_corrupt", "corrupt"),
        ("mc_seed", "seed"),
        ("mc_epochs", "epochs"),
        ("mc_reveal", "reveal_mode"),
        ("mc_probes", "probes"),
        ("mc_probe_steps", "probe_steps"),
        ("mc_prefix", "prefix_steps"),
        ("mc_byz_budget", "byz_budget"),
    ):
        value = getattr(args, attr)
        if value is not None:
            kw[field_name] = value
    cfg = MCConfig(**kw)
    result = run_modelcheck(cfg, repro_path=args.mc_repro)
    d = result.to_dict()
    violations = [] if result.clean else [_mc_violation(result)]
    too_few = (
        result.clean
        and not result.truncated
        and d["explored"] < args.mc_min_states
    )

    if args.trace:
        from .. import obs

        rec = obs.enable(args.trace)
        rec.event(
            "mc_run",
            explored=d["explored"],
            deduped=d["deduped"],
            dpor_pruned=d["dpor_pruned"],
            naive=d["naive"],
            reduction=d["reduction"],
            truncated=d["truncated"],
            probe_runs=d["probe_runs"],
            probe_actions=d["probe_actions"],
            shrink_replays=d["shrink_replays"],
            config=d["config"],
            violation=(result.violation or {}).get("kind"),
            repro_path=d["repro_path"],
            wall=d["wall"],
        )
        obs.disable()

    if fmt == "json":
        print(
            json.dumps(
                {
                    "version": 1,
                    "mc": d,
                    "violations": [v.as_dict() for v in violations],
                    "ok": result.clean and not too_few,
                },
                indent=2,
            )
        )
    elif fmt == "sarif":

        class _McRule:
            name = "modelcheck"
            description = (
                "bounded schedule-space model checking: every "
                "inequivalent delivery interleaving up to the depth "
                "bound preserves the protocol safety invariants"
            )

        print(json.dumps(_sarif(violations, [], [_McRule()]), indent=2))
    else:
        print(
            f"badgermc {cfg.protocol}: {d['explored']} state(s) explored "
            f"(naive {d['naive']}, {d['reduction']:.1f}x reduction, "
            f"{d['deduped']} deduped, {d['dpor_pruned']} DPOR-pruned"
            f"{', TRUNCATED' if d['truncated'] else ''}), "
            f"{d['probe_runs']} probe(s) / {d['probe_actions']} "
            f"deliveries, {d['wall']:.1f}s"
        )
        for v in violations:
            print(v.render())
        if violations:
            if d["repro_path"]:
                print(
                    f"repro written to {d['repro_path']} (replay: "
                    f"python -m hbbft_tpu.harness.scenarios "
                    f"--replay-trace {d['repro_path']})"
                )
        elif too_few:
            pass
        else:
            print("modelcheck clean")
    if too_few:
        print(
            f"modelcheck: only {d['explored']} state(s) explored "
            f"(--mc-min-states {args.mc_min_states}) — degenerate search",
            file=sys.stderr,
        )
        return 1
    return 1 if violations else 0


def _run_stallcheck(
    test_expr: str, fmt: str, budget_s: Optional[float] = None
) -> int:
    """Drive ``pytest --stallcheck`` in a subprocess (the
    ``Handle._run`` patch must live in the process that runs the
    tests), collect the JSONL report and render the stalls with the
    usual formatters."""
    import shlex
    import subprocess
    import tempfile

    from . import stallcheck as _sc

    repo_root = os.path.dirname(os.path.dirname(_HERE))
    with tempfile.TemporaryDirectory() as td:
        out = os.path.join(td, "stallcheck.jsonl")
        env = dict(os.environ)
        env[_sc.OUT_ENV] = out
        if budget_s is not None:
            env[_sc.BUDGET_ENV] = str(budget_s)
        cmd = [
            sys.executable,
            "-m",
            "pytest",
            "-q",
            "-p",
            "no:cacheprovider",
            "--stallcheck",
            *shlex.split(test_expr),
        ]
        proc = subprocess.run(cmd, env=env, cwd=repo_root)
        reports = _sc.load_reports(out)

    violations = [r.as_violation() for r in reports]
    if fmt == "json":
        print(
            json.dumps(
                {
                    "version": 1,
                    "violations": [v.as_dict() for v in violations],
                    "pytest_exit": proc.returncode,
                    "ok": not violations and proc.returncode == 0,
                },
                indent=2,
            )
        )
    elif fmt == "sarif":

        class _ScRule:
            name = "stallcheck"
            description = (
                "event-loop stall sanitizer: no callback blocks the "
                "loop past the budget"
            )

        print(json.dumps(_sarif(violations, [], [_ScRule()]), indent=2))
    else:
        for v in violations:
            print(v.render())
            for hop_path, hop_line, note in v.flow or ():
                print(f"    flow: {hop_path}:{hop_line}: {note}")
        if violations:
            print(f"\n{len(violations)} stall(s)")
        else:
            print("stallcheck clean")
    return 1 if (violations or proc.returncode) else 0


def _counts(violations: List[Violation]) -> dict:
    counts: dict = {}
    for v in violations:
        counts[v.rule] = counts.get(v.rule, 0) + 1
    return counts


def _sarif_location(path: str, line: int, col: int = 0) -> dict:
    return {
        "physicalLocation": {
            "artifactLocation": {"uri": path},
            "region": {
                "startLine": max(line, 1),
                "startColumn": col + 1,
            },
        }
    }


def _sarif(new: List[Violation], errors: List[str], rules) -> dict:
    """SARIF 2.1.0 — the minimal subset GitHub code scanning renders
    as inline PR annotations.  Dataflow findings additionally carry
    ``codeFlows``/``threadFlows`` so viewers render the full
    source→sanitizer→sink path."""
    results = []
    for v in new:
        result = {
            "ruleId": v.rule,
            "level": "error",
            "message": {"text": v.message},
            "locations": [_sarif_location(v.path, v.line, v.col)],
        }
        if v.flow:
            result["codeFlows"] = [
                {
                    "threadFlows": [
                        {
                            "locations": [
                                {
                                    "location": {
                                        **_sarif_location(hop_path, hop_line),
                                        "message": {"text": note},
                                    }
                                }
                                for hop_path, hop_line, note in v.flow
                            ]
                        }
                    ]
                }
            ]
        results.append(result)
    for e in errors:
        path, _, msg = e.partition(": ")
        results.append(
            {
                "ruleId": "parse-error",
                "level": "error",
                "message": {"text": msg or e},
                "locations": [
                    {
                        "physicalLocation": {
                            "artifactLocation": {"uri": path},
                            "region": {"startLine": 1, "startColumn": 1},
                        }
                    }
                ],
            }
        )
    return {
        "version": "2.1.0",
        "$schema": "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/Schemata/sarif-schema-2.1.0.json",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "badgerlint",
                        "rules": [
                            {
                                "id": r.name,
                                "shortDescription": {"text": r.description},
                            }
                            for r in rules
                        ],
                    }
                },
                "results": results,
            }
        ],
    }
