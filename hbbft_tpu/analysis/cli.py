"""The badgerlint CLI.

::

    python -m hbbft_tpu.analysis [paths...]          # human output
    python -m hbbft_tpu.analysis --json [paths...]   # CI / pre-commit
    python -m hbbft_tpu.analysis --write-baseline    # re-baseline (reviewed!)

Exit codes: 0 clean (baselined violations allowed), 1 new violations
or parse errors, 2 usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from .core import Baseline, Violation, lint_paths
from .rules import all_rules

_HERE = os.path.dirname(os.path.abspath(__file__))
DEFAULT_BASELINE = os.path.join(_HERE, "baseline.json")


def _default_paths() -> List[str]:
    """The hbbft_tpu package directory itself."""
    return [os.path.dirname(_HERE)]


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m hbbft_tpu.analysis",
        description="badgerlint — AST invariant checks for hbbft_tpu",
    )
    parser.add_argument(
        "paths", nargs="*", help="files/dirs to lint (default: the package)"
    )
    parser.add_argument("--json", action="store_true", help="machine output")
    parser.add_argument(
        "--baseline",
        default=DEFAULT_BASELINE,
        help="baseline file (default: the checked-in one)",
    )
    parser.add_argument(
        "--no-baseline",
        action="store_true",
        help="report baselined violations as failures too",
    )
    parser.add_argument(
        "--write-baseline",
        metavar="JUSTIFICATION",
        help="write every current violation to the baseline file with "
        "this justification and exit 0",
    )
    parser.add_argument(
        "--select",
        help="comma-separated rule names to run (default: all)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="list rules and exit"
    )
    args = parser.parse_args(argv)

    rules = all_rules()
    if args.list_rules:
        for r in rules:
            print(f"{r.name:14s} {r.description}")
        return 0
    if args.select:
        wanted = {s.strip() for s in args.select.split(",")}
        unknown = wanted - {r.name for r in rules}
        if unknown:
            print(f"unknown rule(s): {', '.join(sorted(unknown))}", file=sys.stderr)
            return 2
        rules = [r for r in rules if r.name in wanted]

    paths = args.paths or _default_paths()
    for p in paths:
        if not os.path.exists(p):
            print(f"no such path: {p}", file=sys.stderr)
            return 2

    violations, errors = lint_paths(paths, rules)

    if args.write_baseline is not None:
        bl = Baseline.from_violations(violations, args.write_baseline)
        bl.save(args.baseline)
        print(
            f"wrote {len(bl.entries)} baseline entr"
            f"{'y' if len(bl.entries) == 1 else 'ies'} to {args.baseline}"
        )
        return 0

    baseline = Baseline()
    if not args.no_baseline and os.path.exists(args.baseline):
        baseline = Baseline.load(args.baseline)
    new, baselined = baseline.split(violations)

    if args.json:
        print(
            json.dumps(
                {
                    "version": 1,
                    "violations": [v.as_dict() for v in new],
                    "baselined": [v.as_dict() for v in baselined],
                    "errors": errors,
                    "counts": _counts(new),
                    "ok": not new and not errors,
                },
                indent=2,
            )
        )
    else:
        for v in new:
            print(v.render())
        for e in errors:
            print(e)
        if new or errors:
            print(
                f"\n{len(new)} violation(s)"
                + (f", {len(errors)} parse error(s)" if errors else "")
                + (f" ({len(baselined)} baselined)" if baselined else "")
            )
        else:
            suffix = f" ({len(baselined)} baselined)" if baselined else ""
            print(f"clean{suffix}")
    return 1 if (new or errors) else 0


def _counts(violations: List[Violation]) -> dict:
    counts: dict = {}
    for v in violations:
        counts[v.rule] = counts.get(v.rule, 0) + 1
    return counts
