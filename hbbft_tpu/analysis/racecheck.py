"""Eraser-style runtime lockset checker for the staged flush pipeline.

The static passes (``thread-shared-state``, ``lock-order``,
``atomic-cache``) see the module-level picture but are blind to
aliasing through locals, dynamic dispatch and instance attributes.
This module covers the other half at runtime, with the classic Eraser
algorithm [Savage et al., SOSP '97] adapted to Python's builtins:

- **Tracked locks.**  :class:`TrackedLock` wraps a real
  ``threading.Lock``/``RLock`` and maintains a per-thread *held set*
  (CPython's ``_thread.lock`` is a C type whose methods cannot be
  patched, so the checker rebinds the module globals that *hold* the
  locks rather than patching lock methods).
- **Tracked containers.**  :class:`TrackedDict` / :class:`TrackedSet`
  / :class:`TrackedList` subclass the builtins and record
  ``(thread, lockset, is_write)`` per access before delegating.
- **Lockset refinement.**  Each tracked variable moves through
  Virgin → Exclusive(first thread) → Shared → Shared-Modified.  Its
  candidate lockset ``C(v)`` starts as the held set at the first
  cross-thread access and is intersected with the held set on every
  later one; an empty ``C(v)`` on a Shared-Modified variable is a
  candidate race, reported once per (variable, site) as a structured
  :class:`~hbbft_tpu.analysis.core.Violation` (rule ``racecheck``) so
  the human/JSON/SARIF renderers work unchanged.

Two front doors:

- ``pytest --racecheck`` (``tests/conftest.py``): every test runs
  between :func:`enable` / :func:`disable`; candidate races accumulate
  into ``$HBBFT_TPU_RACECHECK_OUT`` (JSONL, one report per line) and
  fail the run in the conftest hook.
- ``python -m hbbft_tpu.analysis --racecheck <test-expr>``: runs the
  pytest expression in a subprocess with the env wiring above and
  renders the collected reports like any other lint violation.

What :func:`enable` shims — exactly the shared-state surface the
static inventory mapped (plus the live instances statics cannot see):
the ``staging`` / ``pallas_ec`` / ``packed_msm`` / ``rs`` /
``gf256_jax`` / ``recorder`` module locks, the ``_EXEC_MEM`` /
``_WARM_SEEN`` / ``_RHO_STATE`` caches, ``staging._BUFFERS``'s pool
dict+lock, a live ``staging._STAGER`` and ``recorder.ACTIVE``, and —
via the ``transport/tcp._TRACK_NODE`` constructor hook — the
per-connection state (``_writers``/``outputs``/``faults``) of every
``TcpNode`` built inside the instrumented window.  After
:func:`disable` the plain builtins are rebound (``dict(tracked)``), so
warm caches survive the instrumented window byte-for-byte.

Known gaps, by design: a module global rebound *after* enable (e.g.
``_RHO_STATE`` rebuilt from ``None``) replaces the tracked container —
the window closes until the next :func:`enable`; ``json``'s C encoder
may iterate dict subclasses without calling the overridden methods
(missed read records, never a crash).
"""

from __future__ import annotations

import json
import os
import sys
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, FrozenSet, List, Optional, Tuple

from .core import Violation

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_PKG_ROOT = os.path.join(_REPO_ROOT, "hbbft_tpu")
_SELF = os.path.abspath(__file__)

OUT_ENV = "HBBFT_TPU_RACECHECK_OUT"


def _site() -> Tuple[str, int]:
    """(path, line) of the instrumented access — the innermost frame
    that is neither this module nor the interpreter's threading
    machinery.  Paths render package-relative (``ops/packed_msm.py``)
    to match the static rules' violations."""
    f = sys._getframe(1)
    while f is not None:
        fn = f.f_code.co_filename
        if os.path.abspath(fn) != _SELF and "threading" not in os.path.basename(fn):
            path = os.path.abspath(fn)
            if path.startswith(_PKG_ROOT + os.sep):
                return os.path.relpath(path, _PKG_ROOT), f.f_lineno
            if path.startswith(_REPO_ROOT + os.sep):
                return os.path.relpath(path, _REPO_ROOT), f.f_lineno
            return os.path.basename(path), f.f_lineno
        f = f.f_back
    return "<unknown>", 0


@dataclass
class RaceReport:
    """One candidate race: a Shared-Modified variable whose candidate
    lockset refined to empty."""

    var: str
    path: str
    line: int
    thread: str
    write: bool
    first_thread: str
    threads: Tuple[str, ...]

    def message(self) -> str:
        kind = "write" if self.write else "read"
        return (
            f"candidate race on '{self.var}': un-locked {kind} on thread "
            f"'{self.thread}' after accesses from "
            f"{{{', '.join(repr(t) for t in self.threads)}}} share no "
            "common lock — hold one lock across every access"
        )

    def as_dict(self) -> Dict[str, Any]:
        return {
            "var": self.var,
            "path": self.path,
            "line": self.line,
            "thread": self.thread,
            "write": self.write,
            "first_thread": self.first_thread,
            "threads": list(self.threads),
            "message": self.message(),
        }

    def as_violation(self) -> Violation:
        return Violation(
            rule="racecheck",
            path=self.path,
            line=self.line,
            col=0,
            message=self.message(),
        )


# Eraser states
_VIRGIN = 0
_EXCLUSIVE = 1
_SHARED = 2
_SHARED_MOD = 3


@dataclass
class _VarState:
    state: int = _VIRGIN
    first_thread: str = ""
    threads: set = field(default_factory=set)
    lockset: Optional[FrozenSet[str]] = None  # C(v); None until refined


class TrackedLock:
    """Wraps a real ``threading.Lock``/``RLock``; bookkeeps the calling
    thread's held set (reentrant depth counted, so an RLock acquired
    twice leaves the set only on the final release).  The checker never
    changes blocking semantics — every acquire/release delegates."""

    def __init__(self, raw, name: str, checker: "RaceChecker"):
        self._raw = raw
        self._name = name
        self._chk = checker

    def acquire(self, *a, **kw):
        got = self._raw.acquire(*a, **kw)
        if got:
            self._chk._push_lock(self._name)
        return got

    def release(self):
        self._chk._pop_lock(self._name)
        return self._raw.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._raw.locked()

    def __repr__(self):
        return f"TrackedLock({self._name!r}, {self._raw!r})"


class TrackedDict(dict):
    """A dict that records each access against the checker before
    delegating.  Mutators record writes; lookups record reads."""

    __slots__ = ("_chk", "_name")

    def __init__(self, chk: "RaceChecker", name: str, *a, **kw):
        self._chk = chk
        self._name = name
        super().__init__(*a, **kw)

    def _rec(self, write: bool) -> None:
        self._chk._record(self._name, write)

    def __getitem__(self, k):
        self._rec(False)
        return super().__getitem__(k)

    def __contains__(self, k):
        self._rec(False)
        return super().__contains__(k)

    def get(self, k, default=None):
        self._rec(False)
        return super().get(k, default)

    def __iter__(self):
        self._rec(False)
        return super().__iter__()

    def items(self):
        self._rec(False)
        return super().items()

    def values(self):
        self._rec(False)
        return super().values()

    def keys(self):
        self._rec(False)
        return super().keys()

    def __setitem__(self, k, v):
        self._rec(True)
        super().__setitem__(k, v)

    def __delitem__(self, k):
        self._rec(True)
        super().__delitem__(k)

    def setdefault(self, k, default=None):
        self._rec(True)
        return super().setdefault(k, default)

    def pop(self, *a):
        self._rec(True)
        return super().pop(*a)

    def popitem(self):
        self._rec(True)
        return super().popitem()

    def update(self, *a, **kw):
        self._rec(True)
        super().update(*a, **kw)

    def clear(self):
        self._rec(True)
        super().clear()


class TrackedSet(set):
    __slots__ = ("_chk", "_name")

    def __init__(self, chk: "RaceChecker", name: str, *a):
        self._chk = chk
        self._name = name
        super().__init__(*a)

    def _rec(self, write: bool) -> None:
        self._chk._record(self._name, write)

    def __contains__(self, v):
        self._rec(False)
        return super().__contains__(v)

    def __iter__(self):
        self._rec(False)
        return super().__iter__()

    def add(self, v):
        self._rec(True)
        super().add(v)

    def discard(self, v):
        self._rec(True)
        super().discard(v)

    def remove(self, v):
        self._rec(True)
        super().remove(v)

    def update(self, *a):
        self._rec(True)
        super().update(*a)

    def clear(self):
        self._rec(True)
        super().clear()


class TrackedList(list):
    __slots__ = ("_chk", "_name")

    def __init__(self, chk: "RaceChecker", name: str, *a):
        self._chk = chk
        self._name = name
        super().__init__(*a)

    def _rec(self, write: bool) -> None:
        self._chk._record(self._name, write)

    def __getitem__(self, i):
        self._rec(False)
        return super().__getitem__(i)

    def __iter__(self):
        self._rec(False)
        return super().__iter__()

    def __setitem__(self, i, v):
        self._rec(True)
        super().__setitem__(i, v)

    def append(self, v):
        self._rec(True)
        super().append(v)

    def extend(self, it):
        self._rec(True)
        super().extend(it)

    def insert(self, i, v):
        self._rec(True)
        super().insert(i, v)

    def pop(self, *a):
        self._rec(True)
        return super().pop(*a)

    def remove(self, v):
        self._rec(True)
        super().remove(v)

    def clear(self):
        self._rec(True)
        super().clear()


class RaceChecker:
    """The lockset state machine + the shim installer.

    Usable standalone in tests (``chk = RaceChecker();
    d = chk.track_dict({}, "mine")``) or process-wide via the
    module-level :func:`enable` / :func:`disable` pair."""

    def __init__(self) -> None:
        # the checker's OWN synchronization is a raw RLock created
        # before any shimming — it must never appear in held sets
        self._mu = threading.RLock()
        self._tls = threading.local()
        self._vars: Dict[str, _VarState] = {}
        self.reports: List[RaceReport] = []
        self._seen: set = set()  # (var, path, line) dedupe
        self.active = True
        self._shims: List[Tuple[Any, str, Any]] = []  # (obj, attr, original)

    # -- held-set bookkeeping (thread-local, no lock needed) ----------------

    def _held_map(self) -> Dict[str, int]:
        m = getattr(self._tls, "held", None)
        if m is None:
            m = {}
            self._tls.held = m
        return m

    def _push_lock(self, name: str) -> None:
        m = self._held_map()
        m[name] = m.get(name, 0) + 1

    def _pop_lock(self, name: str) -> None:
        m = self._held_map()
        n = m.get(name, 0) - 1
        if n <= 0:
            m.pop(name, None)
        else:
            m[name] = n

    def held(self) -> FrozenSet[str]:
        return frozenset(self._held_map())

    # -- the Eraser state machine -------------------------------------------

    def _record(self, var: str, write: bool) -> None:
        if not self.active:
            return
        tname = threading.current_thread().name
        held = self.held()
        with self._mu:
            st = self._vars.get(var)
            if st is None:
                st = self._vars[var] = _VarState()
            st.threads.add(tname)
            if st.state == _VIRGIN:
                st.state = _EXCLUSIVE
                st.first_thread = tname
                return
            if st.state == _EXCLUSIVE:
                if tname == st.first_thread:
                    return
                # first cross-thread access: start lockset refinement
                st.lockset = held
                st.state = _SHARED_MOD if write else _SHARED
            else:
                st.lockset = (
                    held if st.lockset is None else st.lockset & held
                )
                if write:
                    st.state = _SHARED_MOD
            if st.state == _SHARED_MOD and not st.lockset:
                path, line = _site()
                key = (var, path, line)
                if key in self._seen:
                    return
                self._seen.add(key)
                self.reports.append(
                    RaceReport(
                        var=var,
                        path=path,
                        line=line,
                        thread=tname,
                        write=write,
                        first_thread=st.first_thread,
                        threads=tuple(sorted(st.threads)),
                    )
                )

    # -- ad-hoc tracking (fixtures, instance attributes) --------------------

    def track_lock(self, lock, name: str) -> TrackedLock:
        if isinstance(lock, TrackedLock):
            return lock
        return TrackedLock(lock, name, self)

    def track_dict(self, d: dict, name: str) -> TrackedDict:
        if isinstance(d, TrackedDict):
            return d
        return TrackedDict(self, name, d)

    def track_set(self, s: set, name: str) -> TrackedSet:
        if isinstance(s, TrackedSet):
            return s
        return TrackedSet(self, name, s)

    def track_list(self, lst: list, name: str) -> TrackedList:
        if isinstance(lst, TrackedList):
            return lst
        return TrackedList(self, name, lst)

    # -- shim installation ---------------------------------------------------

    def _shim(self, obj: Any, attr: str, wrapped: Any) -> None:
        self._shims.append((obj, attr, getattr(obj, attr)))
        setattr(obj, attr, wrapped)

    def install(self) -> None:
        """Shim the package's shared-state surface (see module doc).
        Imports lazily so the checker works in a process that never
        touched the ops layer."""
        from ..crypto import rs
        from ..obs import recorder
        from ..ops import gf256_jax, packed_msm, pallas_ec, staging
        from ..parallel import mesh as _mesh
        from ..recover import wal as _wal
        from ..transport import tcp as _tcp

        lock_sites = [
            (staging, "_STAGER_LOCK", "ops/staging._STAGER_LOCK"),
            (pallas_ec, "_EXEC_LOCK", "ops/pallas_ec._EXEC_LOCK"),
            (pallas_ec, "_FIELD_LOCK", "ops/pallas_ec._FIELD_LOCK"),
            (packed_msm, "_STATE_LOCK", "ops/packed_msm._STATE_LOCK"),
            (rs, "_TABLE16_LOCK", "crypto/rs._TABLE16_LOCK"),
            (gf256_jax, "_BITS16_LOCK", "ops/gf256_jax._BITS16_LOCK"),
            (recorder, "_SWITCH_LOCK", "obs/recorder._SWITCH_LOCK"),
            (_mesh, "_RUNNERS_LOCK", "parallel/mesh._RUNNERS_LOCK"),
        ]
        for mod, attr, name in lock_sites:
            self._shim(mod, attr, self.track_lock(getattr(mod, attr), name))

        self._shim(
            pallas_ec,
            "_EXEC_MEM",
            self.track_dict(pallas_ec._EXEC_MEM, "ops/pallas_ec._EXEC_MEM"),
        )
        # mesh runner cache: prewarm threads and the flush path both
        # build/look up sharded runners keyed by (mesh, shape, engine)
        self._shim(
            _mesh,
            "_RUNNERS",
            self.track_dict(_mesh._RUNNERS, "parallel/mesh._RUNNERS"),
        )
        self._shim(
            packed_msm,
            "_WARM_SEEN",
            self.track_set(packed_msm._WARM_SEEN, "ops/packed_msm._WARM_SEEN"),
        )
        if isinstance(packed_msm._RHO_STATE, dict):
            self._shim(
                packed_msm,
                "_RHO_STATE",
                self.track_dict(
                    packed_msm._RHO_STATE, "ops/packed_msm._RHO_STATE"
                ),
            )

        # live instances the static passes cannot see
        pool = staging._BUFFERS
        self._shim(
            pool, "_lock",
            self.track_lock(pool._lock, "ops/staging.BufferPool._lock"),
        )
        self._shim(
            pool, "_free",
            self.track_dict(pool._free, "ops/staging.BufferPool._free"),
        )
        stager = staging._STAGER
        if stager is not None:
            self._shim(
                stager, "_lock",
                self.track_lock(stager._lock, "ops/staging.Stager._lock"),
            )
        # per-connection transport state: every TcpNode constructed while
        # the checker is installed gets its connection-facing containers
        # tracked (the recv loops / accept callbacks touch them from
        # whatever thread runs the event loop; fuzz/scenario harnesses
        # drive multiple loops from worker threads)
        def _track_tcp_node(node, _chk=self):
            node._writers = _chk.track_dict(
                node._writers, "transport/tcp.TcpNode._writers"
            )
            node.outputs = _chk.track_list(
                node.outputs, "transport/tcp.TcpNode.outputs"
            )
            node.faults = _chk.track_list(
                node.faults, "transport/tcp.TcpNode.faults"
            )
            node._replay = _chk.track_dict(
                node._replay, "transport/tcp.TcpNode._replay"
            )

        self._shim(_tcp, "_TRACK_NODE", _track_tcp_node)

        # WAL writers: the protocol pump appends while the
        # ``hbbft-wal-sync`` daemon fsyncs — their shared lock is
        # tracked per instance via the same constructor-hook pattern
        def _track_wal(writer, _chk=self):
            writer._lock = _chk.track_lock(
                writer._lock, "recover/wal.WalWriter._lock"
            )

        self._shim(_wal, "_TRACK_WAL", _track_wal)

        rec = recorder.ACTIVE
        if rec is not None:
            self._shim(
                rec, "_lock",
                self.track_lock(rec._lock, "obs/recorder.Recorder._lock"),
            )
            self._shim(
                rec, "events",
                self.track_list(rec.events, "obs/recorder.Recorder.events"),
            )
            self._shim(
                rec, "counters",
                self.track_dict(rec.counters, "obs/recorder.Recorder.counters"),
            )
            self._shim(
                rec, "_hists",
                self.track_dict(rec._hists, "obs/recorder.Recorder._hists"),
            )

    def uninstall(self) -> None:
        """Undo every shim, newest first.  Tracked containers rebind as
        plain builtins built from their CURRENT contents (an executable
        loaded during the instrumented window stays cached); tracked
        locks rebind to the original lock object they delegated to, so
        no held state is lost."""
        self.active = False
        for obj, attr, original in reversed(self._shims):
            current = getattr(obj, attr)
            if isinstance(current, TrackedDict):
                setattr(obj, attr, dict(current))
            elif isinstance(current, TrackedSet):
                setattr(obj, attr, set(current))
            elif isinstance(current, TrackedList):
                setattr(obj, attr, list(current))
            elif isinstance(current, TrackedLock):
                setattr(obj, attr, current._raw)
            elif attr in ("_TRACK_NODE", "_TRACK_WAL"):
                # the constructor hooks are plain callables we set —
                # restore the originals (None) so nodes/writers built
                # after disable() are untracked
                setattr(obj, attr, original)
            else:
                # product code rebound the global mid-window (documented
                # gap: e.g. _RHO_STATE reset by a test) — leave its value
                pass
        self._shims.clear()


# ---------------------------------------------------------------------------
# Process-wide switchboard (refcounted: nested enables share one checker)
# ---------------------------------------------------------------------------

_ACTIVE: Optional[RaceChecker] = None
_DEPTH = 0
_SWITCH = threading.Lock()


def active() -> Optional[RaceChecker]:
    return _ACTIVE


def enable() -> RaceChecker:
    """Install the process-wide checker (idempotent/refcounted)."""
    global _ACTIVE, _DEPTH
    with _SWITCH:
        if _ACTIVE is None:
            chk = RaceChecker()
            chk.install()
            _ACTIVE = chk
            _DEPTH = 0
        _DEPTH += 1
        return _ACTIVE


def disable() -> List[RaceReport]:
    """Drop one enable; on the last one, uninstall every shim, append
    the collected reports to ``$HBBFT_TPU_RACECHECK_OUT`` (JSONL) when
    set, and return them."""
    global _ACTIVE, _DEPTH
    with _SWITCH:
        if _ACTIVE is None:
            return []
        _DEPTH -= 1
        if _DEPTH > 0:
            return list(_ACTIVE.reports)
        chk = _ACTIVE
        _ACTIVE = None
    chk.uninstall()
    out = os.environ.get(OUT_ENV)
    if out and chk.reports:
        with open(out, "a") as fh:
            for r in chk.reports:
                fh.write(json.dumps(r.as_dict(), sort_keys=True) + "\n")
    return list(chk.reports)


def load_reports(path: str) -> List[RaceReport]:
    """Parse a ``$HBBFT_TPU_RACECHECK_OUT`` JSONL file back into
    reports (the CLI renders them as violations)."""
    reports: List[RaceReport] = []
    try:
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                d = json.loads(line)
                reports.append(
                    RaceReport(
                        var=d["var"],
                        path=d["path"],
                        line=int(d["line"]),
                        thread=d["thread"],
                        write=bool(d["write"]),
                        first_thread=d.get("first_thread", ""),
                        threads=tuple(d.get("threads", ())),
                    )
                )
    except FileNotFoundError:
        pass
    return reports
