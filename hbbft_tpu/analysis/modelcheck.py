"""badgermc — bounded schedule-space model checking for the protocol
state machines.

Every other gate in this tree (the adversarial scenario matrix, the
fuzzer, racecheck/stallcheck) executes exactly one delivery schedule
per seed.  badgermc explores the schedule *space*: a DFS over the
pending-message frontier of a small network (default n=4, f=1, mock
crypto) that visits every inequivalent message-delivery interleaving up
to a depth bound, asserting the safety invariants of
:mod:`hbbft_tpu.harness.mc_net` at every state.

Why this is sound exploration and not wishful replay: the
``step-purity`` rule proves every ``DistAlgorithm.handle_*`` is a pure
message→state→Step transition, and the ``determinism`` rule proves
there is no ambient entropy — so a network state is *exactly* its
canonical digest (``core.digest``), re-executing an action list is
bit-reproducible, and snapshot/restore backtracking visits the same
states a fresh run would.

Reduction, in two layers:

- **state-hash dedup** — schedules that converge to the same canonical
  digest share their future; a revisited state with no more remaining
  depth than before is cut off;
- **sleep-set DPOR** — a commutativity oracle prunes one order of every
  independent pair.  Two actions are independent iff they touch
  different per-link queues *and* different recipient nodes: a delivery
  mutates only its recipient's state, consumes only its own link's
  head, and appends only to its recipient's outgoing links — so
  same-recipient deliveries are ordered (both orders explored) and
  everything else commutes.  Sleep sets are combined with state hashing
  in the standard practical way; the cut is exact for the safety
  predicates here (which read per-node state the oracle keys on).

Byzantine choice points ride the same frontier: under a budget of
``corrupt`` nodes (the highest ids) and ``byz_budget`` adversarial
actions per schedule, the DFS also branches on drop/duplicate/reorder
of corrupt-sender links, forged decryption shares, malformed payloads,
and equivocating per-recipient forgeries.

On violation the schedule is **shrunk**: the tail ``shrink_window``
actions are delta-debugged (ddmin) against a fresh replay per
candidate, with the known-good prefix frozen — the reported trace is
always ≤ ``shrink_window`` actions and deterministically replayable via
``harness/scenarios.py --replay-trace`` on the emitted repro file.

Bounded liveness is probed separately: seeded full-delivery schedules
must drive every honest node to commit within the probe bound
(violations: ``liveness-stall`` — quiescent before the goal — and
``liveness-bound``).  Odd-indexed probes bias delivery against a
random partition cut (one side of the network races ahead) — these
reach deep asymmetric-progress states that neither the depth-bounded
DFS nor uniform random delivery visits, and the safety invariants are
asserted along the way.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from ..core.digest import restore as _loads, snapshot as _dumps
from ..harness.mc_net import (
    Action,
    MCConfig,
    MCNet,
    check_invariants,
    live_done,
    partition_lag,
    random_schedule,
    run_actions,
    save_repro,
    state_key,
)

__all__ = ["MCConfig", "MCResult", "ModelChecker", "run_modelcheck"]


# -- the DPOR commutativity oracle ------------------------------------------


def _footprint_link(act: Action) -> Tuple:
    if act[0] == "forge":  # forges touch no queue: a private pseudo-link
        return ("#forge", act[1], act[2], act[3])
    return (act[1], act[2])


def _footprint_recipient(act: Action) -> Optional[Any]:
    if act[0] == "drop":  # drops mutate no node, only their link
        return None
    return act[2]


def independent(a: Action, b: Action) -> bool:
    """True iff ``a`` and ``b`` commute from any state where both are
    enabled: different per-link queues and different recipient nodes."""
    if _footprint_link(a) == _footprint_link(b):
        return False
    ra, rb = _footprint_recipient(a), _footprint_recipient(b)
    return ra is None or rb is None or ra != rb


# -- results ----------------------------------------------------------------


@dataclass
class MCStats:
    explored: int = 0
    dedup: int = 0
    dpor_pruned: int = 0
    naive: int = 0  # states a no-dedup/no-DPOR DFS would visit (>=)
    probe_runs: int = 0
    probe_actions: int = 0
    shrink_replays: int = 0


@dataclass
class MCResult:
    config: MCConfig
    stats: MCStats
    violation: Optional[Dict[str, Any]] = None
    truncated: bool = False
    wall: float = 0.0
    repro_path: Optional[str] = None

    @property
    def reduction(self) -> float:
        """Measured state reduction vs naive enumeration: the exact
        number of tree nodes a DFS with no dedup and no DPOR would
        visit to the same depth bound (memoized subtree counts; pruned
        subtrees whose size is unknown count 1, so this is a lower
        bound), divided by the states actually explored."""
        return self.stats.naive / max(1, self.stats.explored)

    @property
    def clean(self) -> bool:
        return self.violation is None

    def to_dict(self) -> Dict[str, Any]:
        return {
            "config": self.config.to_dict(),
            "explored": self.stats.explored,
            "deduped": self.stats.dedup,
            "dpor_pruned": self.stats.dpor_pruned,
            "naive": self.stats.naive,
            "probe_runs": self.stats.probe_runs,
            "probe_actions": self.stats.probe_actions,
            "shrink_replays": self.stats.shrink_replays,
            "reduction": round(self.reduction, 3),
            "truncated": self.truncated,
            "wall": round(self.wall, 6),
            "violation": self.violation,
            "repro_path": self.repro_path,
        }


# -- delta debugging --------------------------------------------------------


def ddmin(seq: List[Any], test) -> List[Any]:
    """Zeller-style ddmin: the smallest complement-closed subsequence of
    ``seq`` for which ``test`` still holds.  ``test(seq)`` must be
    True on entry."""
    n = 2
    while len(seq) >= 2:
        chunk = max(1, len(seq) // n)
        reduced = False
        for i in range(0, len(seq), chunk):
            candidate = seq[:i] + seq[i + chunk :]
            if candidate and test(candidate):
                seq = candidate
                n = max(n - 1, 2)
                reduced = True
                break
        if not reduced:
            if n >= len(seq):
                break
            n = min(len(seq), n * 2)
    return seq


# -- the checker ------------------------------------------------------------


class ModelChecker:
    def __init__(self, cfg: MCConfig, repro_path: Optional[str] = None):
        self.cfg = cfg
        self.repro_path = repro_path
        self.stats = MCStats()
        self.violation: Optional[Dict[str, Any]] = None
        self.written_repro: Optional[str] = None
        self.truncated = False
        # (digest, remaining budget) -> naive subtree size.  Keying on
        # the exact budget (not budget dominance) makes the memoized
        # subtree size a pure function of the key, which is what lets
        # the naive-enumeration count be computed exactly alongside the
        # reduced search.
        self._memo: Dict[Tuple[bytes, int], int] = {}
        self._prefix: List[Action] = []
        self._trace: List[Action] = []

    def run(self) -> MCResult:
        t0 = time.perf_counter()
        cfg = self.cfg
        mc = MCNet(cfg)
        viols = check_invariants(mc)
        if viols:
            self._record([], viols)
        if self.violation is None and cfg.prefix_steps:
            rng = random.Random(cfg.prefix_seed)
            trace, viols = random_schedule(mc, rng, cfg.prefix_steps)
            self._prefix = trace
            if viols:
                self._record(trace, viols)
        if self.violation is None:
            self.stats.naive = self._dfs(mc, cfg.depth, frozenset())
        if self.violation is None:
            self._probes()
        return MCResult(
            config=cfg,
            stats=self.stats,
            violation=self.violation,
            truncated=self.truncated,
            wall=time.perf_counter() - t0,
            repro_path=self.written_repro,
        )

    # -- DFS with dedup + sleep sets ------------------------------------

    def _dfs(self, mc: MCNet, budget: int, sleep: frozenset) -> int:
        """Explore below ``mc``; returns the naive subtree size (the
        states an unreduced DFS would visit from here)."""
        if self.violation is not None or self.truncated:
            return 1
        key = (state_key(mc), budget)
        hit = self._memo.get(key)
        if hit is not None:
            self.stats.dedup += 1
            return hit
        self.stats.explored += 1
        if self.stats.explored >= self.cfg.max_states:
            self.truncated = True
            return 1
        if budget == 0:
            self._memo[key] = 1
            return 1
        acts = mc.enabled_actions()
        if not acts:
            self._memo[key] = 1
            return 1
        snap = _dumps(mc)
        done: List[Action] = []
        naive = 1
        for act in acts:
            child = _loads(snap)
            child.apply_action(act)
            if act in sleep:
                # pruned by the commutativity oracle: the commuted
                # order already explored this subtree — charge its
                # memoized size to the naive count (1 if unknown, so
                # the reduction factor stays a lower bound)
                self.stats.dpor_pruned += 1
                naive += self._memo.get((state_key(child), budget - 1), 1)
                continue
            self._trace.append(act)
            viols = check_invariants(child)
            if viols:
                self._record(list(self._prefix) + list(self._trace), viols)
                self._trace.pop()
                return naive
            child_sleep = frozenset(
                b for b in set(sleep) | set(done) if independent(act, b)
            )
            naive += self._dfs(child, budget - 1, child_sleep)
            self._trace.pop()
            if self.violation is not None or self.truncated:
                return naive
            done.append(act)
        self._memo[key] = naive
        return naive

    # -- full-delivery probes (liveness + deep-state safety) -------------

    def _probes(self) -> None:
        cfg = self.cfg
        for i in range(cfg.probes):
            mc = MCNet(cfg)
            rng = random.Random(f"badgermc-probe-{cfg.seed}-{i}")
            # even probes: uniform full delivery; odd probes: full
            # delivery with a lagging partition cut — uniform schedules
            # converge all nodes together and cannot reach divergence
            # bugs that need one side of the network racing ahead
            lagged = partition_lag(rng, cfg.n) if i % 2 else None
            trace, viols = random_schedule(
                mc, rng, cfg.probe_steps, lagged=lagged
            )
            self.stats.probe_runs += 1
            self.stats.probe_actions += len(trace)
            if viols:
                self._record(trace, viols)
                return
            if not live_done(mc):
                kind = (
                    "liveness-bound"
                    if mc.enabled_actions()
                    else "liveness-stall"
                )
                violation = {
                    "kind": kind,
                    "node": None,
                    "detail": (
                        f"probe {i}: full-delivery schedule did not reach "
                        f"the commit goal within {len(trace)} deliveries"
                        + (
                            " (network quiescent)"
                            if kind == "liveness-stall"
                            else ""
                        )
                    ),
                }
                # liveness counterexamples are whole schedules — no
                # window shrink, but still deterministically replayable
                self._finish_violation(trace, violation, shrink=False)
                return

    # -- counterexample minimization + repro emission --------------------

    def _record(self, full_trace: List[Action], viols) -> None:
        self._finish_violation(full_trace, viols[0], shrink=True)

    def _finish_violation(
        self,
        full_trace: List[Action],
        violation: Dict[str, Any],
        shrink: bool,
    ) -> None:
        cfg = self.cfg
        if shrink and full_trace:
            cut = max(0, len(full_trace) - cfg.shrink_window)
            prefix, suffix = full_trace[:cut], full_trace[cut:]

            def still_fails(candidate: List[Action]) -> bool:
                self.stats.shrink_replays += 1
                probe = MCNet(cfg)
                res = run_actions(
                    probe, prefix + candidate, check_from=len(prefix)
                )
                return res.feasible and bool(res.violations)

            if still_fails(suffix):
                suffix = ddmin(suffix, still_fails)
            prefix, suffix = list(prefix), list(suffix)
        else:
            prefix, suffix = [], list(full_trace)
        # pin the exact replay outcome the repro file promises
        final = MCNet(cfg)
        res = run_actions(final, prefix + suffix, check_from=len(prefix))
        if res.violations:
            violation = res.violations[0]
        self.violation = {
            **violation,
            "trace": [list(a) for a in suffix],
            "prefix_len": len(prefix),
            "trace_len": len(suffix),
        }
        if self.repro_path:
            save_repro(
                self.repro_path,
                cfg,
                prefix,
                suffix,
                violation,
                res.digest,
            )
            self.written_repro = self.repro_path


def run_modelcheck(
    cfg: MCConfig, repro_path: Optional[str] = None
) -> MCResult:
    """Run badgermc at ``cfg``; write a repro file on violation when
    ``repro_path`` is given."""
    return ModelChecker(cfg, repro_path=repro_path).run()
