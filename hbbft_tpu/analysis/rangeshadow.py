"""Arbitrary-precision shadow sanitizer — limbprove's runtime dual.

:mod:`rangecheck` proves the kernels' integer ranges statically from
their jaxprs; this module covers the other half at runtime, in the
racecheck/stallcheck mold: every shimmed device kernel re-executes a
*sampled* slice of its work with arbitrary-precision Python ints and
flags any divergence from the device result as a concrete overflow
witness.  A wrapped int32 is invisible on device (no trap, no NaN —
just a wrong residue); against an exact shadow it is a loud diff.

Per-kernel oracles (all exact, all independent of the device path):

- **fr.matmul / fr.add** — sampled output cells recomputed as Python
  ints mod r from the decoded 8-bit limb inputs.
- **sha.device** — the padded block stream is parsed back to the
  original message (the padding is self-describing) and hashed with
  :mod:`hashlib`; batches that are not standard SHA-256 padding are
  skipped, never guessed at.
- **gf.matmul / gf.matmul16** — sampled cells recomputed with the
  host tower (``crypto.rs.gf_mul`` / ``gf16_mul``).
- **ec.g1/g2 msm + the pallas point kernels** — every sampled output
  point is checked against the projective curve identity
  ``Y²Z ≡ X³ + b·Z³ (mod p)`` (b = 4 on G1, 4(1+u) on G2; the
  identity (0:1:0) satisfies it trivially).  A limb that wrapped
  int32 lands off-curve with overwhelming probability.  Small
  multi-scalar multiplications (k ≤ 16) are additionally recomputed
  exactly on the host curve.

Two front doors, shared with racecheck/stallcheck:

- ``pytest --rangecheck`` (``tests/conftest.py``): every test runs
  between :func:`enable` / :func:`disable`; divergences accumulate
  into ``$HBBFT_TPU_RANGECHECK_OUT`` (JSONL) and fail the run.
- ``python -m hbbft_tpu.analysis --rangecheck <test-expr>``: runs the
  expression in a subprocess with the env wiring above and renders
  the collected reports like any other lint violation.

``$HBBFT_TPU_RANGECHECK_SAMPLE`` bounds the cells/points sampled per
kernel call (default 4; sampling is deterministic — evenly strided —
so a failing run replays bit-identically).

:func:`wrap` is public: tests (and future kernels) can wrap any
callable with their own shadow oracle and inherit the report plumbing
— the planted-overflow fixture in ``tests/test_rangecheck.py`` uses
exactly this seam.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .core import Violation

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
_PKG_ROOT = os.path.join(_REPO_ROOT, "hbbft_tpu")
_SELF = os.path.abspath(__file__)

OUT_ENV = "HBBFT_TPU_RANGECHECK_OUT"
SAMPLE_ENV = "HBBFT_TPU_RANGECHECK_SAMPLE"


def _sample_budget() -> int:
    try:
        return max(1, int(os.environ.get(SAMPLE_ENV, "4")))
    except ValueError:
        return 4


def _strides(n: int, k: int) -> List[int]:
    """Up to ``k`` indices evenly strided over ``range(n)`` —
    deterministic sampling, so a failure replays bit-identically."""
    if n <= 0:
        return []
    k = min(k, n)
    return sorted({(i * n) // k for i in range(k)})


def _site() -> Tuple[str, int]:
    """(path, line) of the kernel call site — the innermost frame
    outside this module, package-relative like the static rules."""
    f = sys._getframe(1)
    while f is not None:
        fn = os.path.abspath(f.f_code.co_filename)
        if fn != _SELF:
            if fn.startswith(_PKG_ROOT + os.sep):
                return os.path.relpath(fn, _PKG_ROOT), f.f_lineno
            if fn.startswith(_REPO_ROOT + os.sep):
                return os.path.relpath(fn, _REPO_ROOT), f.f_lineno
            return os.path.basename(fn), f.f_lineno
        f = f.f_back
    return "<unknown>", 0


@dataclass
class ShadowReport:
    """One device/shadow divergence — a concrete overflow witness."""

    kernel: str
    path: str
    line: int
    index: str
    expected: str
    actual: str

    def message(self) -> str:
        return (
            f"shadow divergence in '{self.kernel}' at {self.index}: "
            f"device={self.actual} exact-shadow={self.expected} — "
            "an intermediate wrapped its accumulator dtype; re-run "
            "`python -m hbbft_tpu.analysis --select limb-range` for "
            "the failing obligation and flow"
        )

    def as_dict(self) -> Dict[str, Any]:
        return {
            "kernel": self.kernel,
            "path": self.path,
            "line": self.line,
            "index": self.index,
            "expected": self.expected,
            "actual": self.actual,
            "message": self.message(),
        }

    def as_violation(self) -> Violation:
        return Violation(
            rule="rangecheck",
            path=self.path,
            line=self.line,
            col=0,
            message=self.message(),
        )


# ---------------------------------------------------------------------------
# Limb decoding (exact Python ints; no device math)
# ---------------------------------------------------------------------------


def _u8_int(vec: np.ndarray) -> int:
    """Little-endian base-256 limb vector → int (fr's representation)."""
    return int.from_bytes(np.asarray(vec, dtype=np.uint8).tobytes(), "little")


def _limb_int(vec: np.ndarray) -> int:
    """Little-endian base-2^LIMB_BITS int32 limb vector → int (may be
    negative transiently; exact either way)."""
    from ..ops import limbs as LB

    acc = 0
    shift = 0
    for v in np.asarray(vec).tolist():
        acc += int(v) << shift
        shift += LB.LIMB_BITS
    return acc


# ---------------------------------------------------------------------------
# Per-kernel shadow oracles.  Each takes (numpy args, numpy out) and
# returns a list of (index, expected, actual) mismatches.
# ---------------------------------------------------------------------------

Mismatch = Tuple[str, str, str]


def _shadow_fr_matmul(args: Sequence[np.ndarray], out: np.ndarray) -> List[Mismatch]:
    from ..crypto import fields as F

    a, b = args[0], args[1]
    m, k, p = a.shape[0], a.shape[1], b.shape[1]
    bad: List[Mismatch] = []
    cells = [(i, j) for i in _strides(m, _sample_budget()) for j in _strides(p, 1)]
    for i, j in cells[: _sample_budget()]:
        want = (
            sum(_u8_int(a[i, t]) * _u8_int(b[t, j]) for t in range(k)) % F.R
        )
        got = _u8_int(out[i, j]) % F.R
        if want != got:
            bad.append((f"[{i},{j}]", str(want), str(got)))
    return bad


def _shadow_fr_add(args: Sequence[np.ndarray], out: np.ndarray) -> List[Mismatch]:
    from ..crypto import fields as F
    from ..ops import fr_jax

    a = np.asarray(args[0]).reshape(-1, fr_jax.FR_LIMBS)
    b = np.asarray(args[1]).reshape(-1, fr_jax.FR_LIMBS)
    o = np.asarray(out).reshape(-1, fr_jax.FR_LIMBS)
    bad: List[Mismatch] = []
    for i in _strides(o.shape[0], _sample_budget()):
        want = (_u8_int(a[i]) + _u8_int(b[i])) % F.R
        got = _u8_int(o[i]) % F.R
        if want != got:
            bad.append((f"[{i}]", str(want), str(got)))
    return bad


def _sha_unpad(words: np.ndarray) -> Optional[bytes]:
    """[nblocks, 16] uint32 big-endian words → original message, or
    None when the buffer is not standard SHA-256 padding (skip, never
    guess)."""
    raw = np.asarray(words, dtype=">u4").tobytes()
    bitlen = int.from_bytes(raw[-8:], "big")
    if bitlen % 8:
        return None
    n = bitlen // 8
    if n > len(raw) - 9:
        return None
    msg, pad = raw[:n], raw[n:-8]
    if not pad or pad[0] != 0x80 or any(pad[1:]):
        return None
    return msg


def _shadow_sha(args: Sequence[np.ndarray], out: np.ndarray) -> List[Mismatch]:
    blocks = np.asarray(args[0], dtype=np.uint32)
    digests = np.asarray(out, dtype=np.uint32)
    bad: List[Mismatch] = []
    for i in _strides(blocks.shape[0], _sample_budget()):
        msg = _sha_unpad(blocks[i])
        if msg is None:
            continue
        want = hashlib.sha256(msg).hexdigest()
        got = b"".join(
            int(w).to_bytes(4, "big") for w in digests[i]
        ).hex()
        if want != got:
            bad.append((f"[{i}]", want, got))
    return bad


def _shadow_gf_matmul(args: Sequence[np.ndarray], out: np.ndarray) -> List[Mismatch]:
    from ..crypto import rs as host_rs

    mat = np.asarray(args[0], dtype=np.uint8)
    data = np.asarray(args[1], dtype=np.uint8)
    o = np.asarray(out, dtype=np.uint8)
    bad: List[Mismatch] = []
    cells = [
        (i, j)
        for i in _strides(mat.shape[0], _sample_budget())
        for j in _strides(data.shape[1], 1)
    ]
    for i, j in cells[: _sample_budget()]:
        want = 0
        for t in range(mat.shape[1]):
            want ^= host_rs.gf_mul(int(mat[i, t]), int(data[t, j]))
        if want != int(o[i, j]):
            bad.append((f"[{i},{j}]", str(want), str(int(o[i, j]))))
    return bad


def _shadow_gf16_matmul(args: Sequence[np.ndarray], out: np.ndarray) -> List[Mismatch]:
    from ..crypto import rs as host_rs

    mat = np.asarray(args[0], dtype=np.uint16)
    data = np.asarray(args[1], dtype=np.uint16)
    o = np.asarray(out, dtype=np.uint16)
    bad: List[Mismatch] = []
    cells = [
        (i, j)
        for i in _strides(mat.shape[0], _sample_budget())
        for j in _strides(data.shape[1], 1)
    ]
    for i, j in cells[: _sample_budget()]:
        want = 0
        for t in range(mat.shape[1]):
            want ^= host_rs.gf16_mul(int(mat[i, t]), int(data[t, j]))
        if want != int(o[i, j]):
            bad.append((f"[{i},{j}]", str(want), str(int(o[i, j]))))
    return bad


# -- EC on-curve witness ------------------------------------------------------


def _on_curve_g1(X: int, Y: int, Z: int) -> bool:
    from ..crypto import fields as F

    return (Y * Y * Z - X**3 - 4 * Z**3) % F.P == 0


def _on_curve_g2(X, Y, Z) -> bool:
    from ..crypto import fields as F

    b2 = F.fq2_scalar(F.XI, 4)  # 4(1+u), the twist constant
    lhs = F.fq2_mul(F.fq2_sq(Y), Z)
    rhs = F.fq2_add(
        F.fq2_mul(F.fq2_sq(X), X),
        F.fq2_mul(b2, F.fq2_mul(F.fq2_sq(Z), Z)),
    )
    return F.fq2_sub(lhs, rhs) == (0, 0)


def _point_mismatches(arr: np.ndarray, g2: bool, kernel_L: int) -> List[Mismatch]:
    """On-curve check over every point layout the kernels emit:
    ``[..., 3, L]`` / ``[..., 3, 2, L]`` (XLA) and their tile-major
    transposes ``[..., 3, L, T]`` / ``[..., 3, 2, L, T]``."""
    a = np.asarray(arr)
    s = a.shape
    L = kernel_L
    if g2:
        if len(s) >= 3 and s[-3:] == (3, 2, L):
            pts = a.reshape(-1, 3, 2, L)
        elif len(s) >= 4 and s[-4:-1] == (3, 2, L):
            pts = np.moveaxis(a.reshape(-1, 3, 2, L, s[-1]), -1, 1).reshape(
                -1, 3, 2, L
            )
        else:
            return []
    else:
        if len(s) >= 2 and s[-2:] == (3, L):
            pts = a.reshape(-1, 3, L)
        elif len(s) >= 3 and s[-3:-1] == (3, L):
            pts = np.moveaxis(a.reshape(-1, 3, L, s[-1]), -1, 1).reshape(
                -1, 3, L
            )
        else:
            return []
    bad: List[Mismatch] = []
    for i in _strides(pts.shape[0], _sample_budget()):
        if g2:
            X = (_limb_int(pts[i, 0, 0]), _limb_int(pts[i, 0, 1]))
            Y = (_limb_int(pts[i, 1, 0]), _limb_int(pts[i, 1, 1]))
            Z = (_limb_int(pts[i, 2, 0]), _limb_int(pts[i, 2, 1]))
            ok = _on_curve_g2(X, Y, Z)
        else:
            X, Y, Z = (_limb_int(pts[i, j]) for j in range(3))
            ok = _on_curve_g1(X, Y, Z)
        if not ok:
            bad.append(
                (f"point[{i}]", "Y²Z ≡ X³ + b·Z³ (mod p)", "off-curve")
            )
    return bad


def _shadow_msm(g2: bool, exact_k: int = 16):
    """Shadow for the jitted msm entries: on-curve witness always;
    exact host-curve recomputation when the problem is small."""

    def shadow(args: Sequence[np.ndarray], out: np.ndarray) -> List[Mismatch]:
        from ..ops import ec_jax, limbs as LB

        L = LB.fq().L
        bad = _point_mismatches(out, g2, L)
        pts, bits = np.asarray(args[0]), np.asarray(args[1])
        if pts.ndim >= 2 and pts.shape[0] <= exact_k and not bad:
            from_l = ec_jax.g2_from_limbs if g2 else ec_jax.g1_from_limbs
            try:
                acc = None
                for i in range(pts.shape[0]):
                    s = 0
                    for b in np.asarray(bits[i]).tolist():
                        s = (s << 1) | int(b)
                    term = from_l(pts[i]) * s
                    acc = term if acc is None else acc + term
                want = from_l(out)
                if acc is not None and want != acc:
                    bad.append(("msm", repr(acc), repr(want)))
            except ValueError:
                # inputs off-curve (synthetic test tensors): the
                # witness above is the authority, not the recompute
                pass
        return bad

    return shadow


def _shadow_scalar_mul(args: Sequence[np.ndarray], out: np.ndarray) -> List[Mismatch]:
    from ..ops import limbs as LB

    return _point_mismatches(out, False, LB.fq().L)


# pallas/cached_compiled programs, dispatched by cache name: which
# output leaves carry point limbs, and on which curve
_POINT_PROGRAMS: Tuple[Tuple[str, bool], ...] = (
    ("win_g2", True),
    ("tree_g2", True),
    ("flat_g2", True),
    ("unpack_g2", True),
    ("win_g1", False),
    ("tree_g1", False),
    ("gtree_g1", False),
    ("scan_g1", False),
    ("flat_g1", False),
    ("prod_g1", False),
    ("mesh_prod", False),
    ("unpack_g1", False),
)


# ---------------------------------------------------------------------------
# The checker: report accumulation + shim installation
# ---------------------------------------------------------------------------


class RangeChecker:
    """Holds the divergence reports and the installed shims.  Usable
    standalone in tests or process-wide via :func:`enable` /
    :func:`disable` (same switchboard shape as racecheck)."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self.reports: List[ShadowReport] = []
        self._seen: set = set()
        self.active = True
        self._shims: List[Tuple[Any, str, Any]] = []

    def record(
        self, kernel: str, index: str, expected: str, actual: str
    ) -> None:
        if not self.active:
            return
        path, line = _site()
        key = (kernel, path, line, index)
        with self._mu:
            if key in self._seen:
                return
            self._seen.add(key)
            self.reports.append(
                ShadowReport(
                    kernel=kernel,
                    path=path,
                    line=line,
                    index=index,
                    expected=expected,
                    actual=actual,
                )
            )

    def run_shadow(
        self,
        kernel: str,
        shadow: Callable[[Sequence[np.ndarray], Any], List[Mismatch]],
        args: Sequence[Any],
        out: Any,
    ) -> None:
        try:
            np_args = [np.asarray(a) for a in args]
            np_out = (
                tuple(np.asarray(o) for o in out)
                if isinstance(out, (tuple, list))
                else np.asarray(out)
            )
            for index, expected, actual in shadow(np_args, np_out):
                self.record(kernel, index, expected, actual)
        except Exception as exc:  # oracle bug ≠ product crash
            self.record(kernel, "<shadow-error>", "<no exception>", repr(exc))

    # -- shim installation ---------------------------------------------------

    def _shim(self, obj: Any, attr: str, wrapped: Any) -> None:
        self._shims.append((obj, attr, getattr(obj, attr)))
        setattr(obj, attr, wrapped)

    def install(self) -> None:
        """Shim the device-kernel surface (module-global rebinding, the
        racecheck pattern — the jitted callables cannot be patched in
        place).  Imports lazily so a process that never touches the
        ops layer pays nothing."""
        from ..ops import ec_jax, fr_jax, gf256_jax, pallas_ec, sha256_jax

        for mod, attr, kernel, shadow in (
            (fr_jax, "fr_matmul_device", "fr.matmul", _shadow_fr_matmul),
            (fr_jax, "fr_add_device", "fr.add", _shadow_fr_add),
            (sha256_jax, "sha256_device", "sha.device", _shadow_sha),
            (gf256_jax, "gf_matmul_device", "gf.matmul", _shadow_gf_matmul),
            (gf256_jax, "gf16_matmul_device", "gf.matmul16", _shadow_gf16_matmul),
            (ec_jax, "g1_msm_device", "ec.g1_msm", _shadow_msm(False)),
            (ec_jax, "g2_msm_device", "ec.g2_msm", _shadow_msm(True)),
            (ec_jax, "g1_scalar_mul_device", "ec.g1_scalar_mul", _shadow_scalar_mul),
        ):
            self._shim(mod, attr, wrap(kernel, getattr(mod, attr), shadow))

        orig_cc = pallas_ec.cached_compiled

        def cached_compiled(name, fn, *args, key_parts=None, donate=()):
            out = orig_cc(name, fn, *args, key_parts=key_parts, donate=donate)
            chk = active()
            if chk is not None and chk.active:
                for prefix, g2 in _POINT_PROGRAMS:
                    if str(name).startswith(prefix):
                        chk.run_shadow(
                            f"pallas.{name}",
                            lambda a, o, _g2=g2: _leaf_points(o, _g2),
                            (),
                            out,
                        )
                        break
            return out

        self._shim(pallas_ec, "cached_compiled", cached_compiled)

    def uninstall(self) -> None:
        self.active = False
        for obj, attr, original in reversed(self._shims):
            setattr(obj, attr, original)
        self._shims.clear()


def _leaf_points(out: Any, g2: bool) -> List[Mismatch]:
    from ..ops import limbs as LB

    L = LB.fq().L
    leaves = out if isinstance(out, tuple) else (out,)
    bad: List[Mismatch] = []
    for leaf in leaves:
        bad.extend(_point_mismatches(np.asarray(leaf), g2, L))
    return bad


def wrap(
    kernel: str,
    fn: Callable[..., Any],
    shadow: Callable[[Sequence[np.ndarray], Any], List[Mismatch]],
) -> Callable[..., Any]:
    """Public seam: wrap any callable with an exact-shadow oracle.
    When no checker is enabled the wrapper is a passthrough; when one
    is, each call's (args, out) is handed to ``shadow`` and every
    returned ``(index, expected, actual)`` mismatch becomes a report."""

    def wrapped(*args, **kwargs):
        out = fn(*args, **kwargs)
        chk = active()
        if chk is not None and chk.active:
            chk.run_shadow(kernel, shadow, args, out)
        return out

    wrapped.__name__ = getattr(fn, "__name__", kernel)
    wrapped.__wrapped__ = fn
    return wrapped


# ---------------------------------------------------------------------------
# Process-wide switchboard (refcounted, racecheck shape)
# ---------------------------------------------------------------------------

_ACTIVE: Optional[RangeChecker] = None
_DEPTH = 0
_SWITCH = threading.Lock()


def active() -> Optional[RangeChecker]:
    return _ACTIVE


def enable() -> RangeChecker:
    """Install the process-wide checker (idempotent/refcounted)."""
    global _ACTIVE, _DEPTH
    with _SWITCH:
        if _ACTIVE is None:
            chk = RangeChecker()
            chk.install()
            _ACTIVE = chk
            _DEPTH = 0
        _DEPTH += 1
        return _ACTIVE


def disable() -> List[ShadowReport]:
    """Drop one enable; on the last, uninstall every shim, append the
    collected reports to ``$HBBFT_TPU_RANGECHECK_OUT`` (JSONL) when
    set, and return them."""
    global _ACTIVE, _DEPTH
    with _SWITCH:
        if _ACTIVE is None:
            return []
        _DEPTH -= 1
        if _DEPTH > 0:
            return list(_ACTIVE.reports)
        chk = _ACTIVE
        _ACTIVE = None
    chk.uninstall()
    out = os.environ.get(OUT_ENV)
    if out and chk.reports:
        with open(out, "a") as fh:
            for r in chk.reports:
                fh.write(json.dumps(r.as_dict(), sort_keys=True) + "\n")
    return list(chk.reports)


def load_reports(path: str) -> List[ShadowReport]:
    """Parse a ``$HBBFT_TPU_RANGECHECK_OUT`` JSONL file back into
    reports (the CLI renders them as violations)."""
    reports: List[ShadowReport] = []
    try:
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                d = json.loads(line)
                reports.append(
                    ShadowReport(
                        kernel=d["kernel"],
                        path=d["path"],
                        line=int(d["line"]),
                        index=d.get("index", ""),
                        expected=d.get("expected", ""),
                        actual=d.get("actual", ""),
                    )
                )
    except FileNotFoundError:
        pass
    return reports
