"""The badgerlint framework: violations, rules, suppression, baseline.

Design (kept deliberately small — this is a project lint, not a
general one):

- A :class:`Rule` owns a name, a human description, and a path scope
  (package-relative prefixes).  ``check(ctx)`` yields
  :class:`Violation`\\ s for one parsed file.
- Paths are normalized **relative to the package root** (the part
  after ``hbbft_tpu/``), so rule scopes and baseline entries are
  stable no matter where the tree is checked out or which directory
  the CLI is invoked from.  Files outside the package (tests,
  examples) get their path relative to the scan root and match no
  scoped rule unless a rule opts in.
- Suppression is per-line: ``# lint: ok(<rule>)`` on the flagged line
  or the line directly above silences that rule there.  Suppressions
  are counted so the CLI can report them.
- The baseline is a checked-in JSON list of intentional violations,
  matched by ``(rule, path, message)`` — line numbers are excluded so
  unrelated edits don't invalidate entries.  Every entry carries a
  mandatory ``justification`` string.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import tokenize
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

# The package this lint suite is scoped to (directory name on disk).
PACKAGE_NAME = "hbbft_tpu"

_SUPPRESS_PREFIX = "# lint: ok("


@dataclasses.dataclass(frozen=True)
class Violation:
    """One rule hit.  ``path`` is package-relative and POSIX-style."""

    rule: str
    path: str
    line: int
    col: int
    message: str
    # Optional source→sink witness path for dataflow rules: a tuple of
    # (path, line, note) hops.  Excluded from equality/baseline identity
    # so flow-note wording can evolve without invalidating entries.
    flow: Optional[Tuple[Tuple[str, int, str], ...]] = dataclasses.field(
        default=None, compare=False
    )

    def key(self) -> Tuple[str, str, str]:
        """Baseline identity: line/col excluded on purpose (see module
        doc)."""
        return (self.rule, self.path, self.message)

    def as_dict(self) -> Dict[str, object]:
        d = dataclasses.asdict(self)
        if self.flow is not None:
            d["flow"] = [
                {"path": p, "line": ln, "note": note} for p, ln, note in self.flow
            ]
        else:
            d.pop("flow")
        return d

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] {self.message}"


class FileContext:
    """Everything a rule needs about one source file."""

    def __init__(self, relpath: str, source: str, tree: Optional[ast.Module] = None):
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree if tree is not None else ast.parse(source)

    def in_dirs(self, prefixes: Sequence[str]) -> bool:
        return any(self.relpath.startswith(p) for p in prefixes)

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def suppressed(self, rule: str, lineno: int) -> bool:
        """``# lint: ok(rule)`` on the line or the line above."""
        for text in (self.line_text(lineno), self.line_text(lineno - 1)):
            idx = text.find(_SUPPRESS_PREFIX)
            while idx != -1:
                end = text.find(")", idx)
                if end != -1:
                    names = text[idx + len(_SUPPRESS_PREFIX) : end]
                    for name in names.split(","):
                        if name.strip() in (rule, "*"):
                            return True
                idx = text.find(_SUPPRESS_PREFIX, idx + 1)
        return False


class Rule:
    """Base class: subclasses set ``name``, ``description``, ``scope``
    (package-relative path prefixes; empty tuple = every file) and
    implement :meth:`check`.

    Whole-project rules additionally override :meth:`begin_run` /
    :meth:`finish_run`: ``begin_run`` resets any cross-file state
    before a lint run, ``check`` accumulates per-file facts, and
    ``finish_run`` yields the violations only visible once every file
    has been seen (e.g. a wire type present in the golden manifest but
    found in no scanned module).  ``finish_run`` violations have no
    enclosing source line, so ``# lint: ok`` cannot silence them — the
    baseline is the only escape hatch.
    """

    name: str = ""
    description: str = ""
    scope: Tuple[str, ...] = ()
    # Whole-project rules reason across files: scoping their input to a
    # subset (e.g. ``--changed``) silently under-reports, so the CLI
    # widens to a full run whenever a changed file is in their domain.
    whole_project: bool = False

    def applies(self, ctx: FileContext) -> bool:
        return not self.scope or ctx.in_dirs(self.scope)

    def check(self, ctx: FileContext) -> Iterable[Violation]:  # pragma: no cover
        raise NotImplementedError

    def begin_run(self) -> None:
        """Reset cross-file state (start of a lint run)."""

    def finish_run(self) -> Iterable[Violation]:
        """Project-level violations, after every file was checked."""
        return ()

    def violation(
        self, ctx: FileContext, node: ast.AST, message: str
    ) -> Violation:
        return Violation(
            rule=self.name,
            path=ctx.relpath,
            line=getattr(node, "lineno", 0),
            col=getattr(node, "col_offset", 0),
            message=message,
        )


# ---------------------------------------------------------------------------
# Baseline
# ---------------------------------------------------------------------------


class Baseline:
    """The checked-in list of intentional violations.

    File format: ``{"version": 1, "entries": [{"rule", "path",
    "message", "justification"}, ...]}``.  An entry with an empty
    justification is rejected at load time — the whole point is that
    every baselined violation says *why* it is fine.
    """

    def __init__(self, entries: Optional[List[Dict[str, str]]] = None):
        self.entries: List[Dict[str, str]] = list(entries or [])
        self._index: Dict[Tuple[str, str, str], Dict[str, str]] = {
            (e["rule"], e["path"], e["message"]): e for e in self.entries
        }

    @classmethod
    def load(cls, path: str) -> "Baseline":
        with open(path, "r") as fh:
            data = json.load(fh)
        entries = data.get("entries", [])
        for e in entries:
            for field in ("rule", "path", "message", "justification"):
                if not e.get(field):
                    raise ValueError(
                        f"baseline entry missing {field!r}: {e!r}"
                    )
        return cls(entries)

    @classmethod
    def from_violations(
        cls, violations: Iterable[Violation], justification: str
    ) -> "Baseline":
        entries = [
            {
                "rule": v.rule,
                "path": v.path,
                "message": v.message,
                "justification": justification,
            }
            for v in violations
        ]
        # de-dup while preserving order (several lines may share a key)
        seen = set()
        uniq = []
        for e in entries:
            k = (e["rule"], e["path"], e["message"])
            if k not in seen:
                seen.add(k)
                uniq.append(e)
        return cls(uniq)

    def save(self, path: str) -> None:
        with open(path, "w") as fh:
            json.dump(
                {"version": 1, "entries": self.entries}, fh, indent=2
            )
            fh.write("\n")

    def covers(self, v: Violation) -> bool:
        return v.key() in self._index

    def split(
        self, violations: Sequence[Violation]
    ) -> Tuple[List[Violation], List[Violation]]:
        """→ (new, baselined)."""
        new, old = [], []
        for v in violations:
            (old if self.covers(v) else new).append(v)
        return new, old


# ---------------------------------------------------------------------------
# Running
# ---------------------------------------------------------------------------


def _check_ctx(ctx: FileContext, rules: Sequence[Rule]) -> List[Violation]:
    """Per-file portion of a run: every applicable rule over one file,
    suppression comments honored.  Callers own begin/finish_run."""
    out: List[Violation] = []
    for rule in rules:
        if not rule.applies(ctx):
            continue
        for v in rule.check(ctx):
            if not ctx.suppressed(v.rule, v.line):
                out.append(v)
    return out


def lint_source(
    source: str,
    relpath: str,
    rules: Sequence[Rule],
) -> List[Violation]:
    """Lint one in-memory source blob under a pretend package-relative
    path (the fixture-test entry point).  This is a complete run: the
    whole-project hooks fire around the single file."""
    ctx = FileContext(relpath, source)
    for rule in rules:
        rule.begin_run()
    out = _check_ctx(ctx, rules)
    for rule in rules:
        out.extend(rule.finish_run())
    out.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    return out


def lint_file(path: str, relpath: str, rules: Sequence[Rule]) -> List[Violation]:
    with tokenize.open(path) as fh:  # honors coding declarations
        source = fh.read()
    return lint_source(source, relpath, rules)


def _package_relpath(abspath: str, root: str) -> str:
    """Path component after the ``hbbft_tpu`` package dir if the file
    is inside it, else the path relative to the scan root."""
    norm = abspath.replace(os.sep, "/")
    marker = "/" + PACKAGE_NAME + "/"
    idx = norm.rfind(marker)
    if idx != -1:
        return norm[idx + len(marker) :]
    return os.path.relpath(abspath, root).replace(os.sep, "/")


def iter_python_files(paths: Sequence[str]) -> Iterator[Tuple[str, str]]:
    """Yield ``(abspath, package_relpath)`` for every .py under the
    given files/directories, sorted for deterministic output."""
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(
                    d for d in dirnames if d not in ("__pycache__", ".git")
                )
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        files.append(os.path.join(dirpath, fn))
        elif p.endswith(".py"):
            files.append(p)
    root = os.path.commonpath([os.path.abspath(p) for p in paths]) if paths else "."
    if os.path.isfile(root):
        root = os.path.dirname(root)
    for f in sorted(set(files)):
        yield os.path.abspath(f), _package_relpath(os.path.abspath(f), root)


def lint_paths(
    paths: Sequence[str], rules: Sequence[Rule]
) -> Tuple[List[Violation], List[str]]:
    """Lint every file under ``paths`` → (violations, parse_errors).

    One whole-project run: ``begin_run`` fires once up front, every
    file goes through ``check``, and ``finish_run`` fires once at the
    end so cross-file rules see the full tree before reporting."""
    violations: List[Violation] = []
    errors: List[str] = []
    for rule in rules:
        rule.begin_run()
    for abspath, relpath in iter_python_files(paths):
        try:
            with tokenize.open(abspath) as fh:
                source = fh.read()
            ctx = FileContext(relpath, source)
        except SyntaxError as exc:
            errors.append(f"{relpath}: syntax error: {exc}")
            continue
        violations.extend(_check_ctx(ctx, rules))
    for rule in rules:
        violations.extend(rule.finish_run())
    violations.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    return violations, errors
