"""Event-loop stall sanitizer — the runtime half of the async-safety
pass.

The static ``async-blocking`` rule proves that no *statically visible*
call chain parks the event loop; this module catches what the call
graph cannot see (dynamic dispatch through untyped hooks, C extensions,
a cold JIT compile, plain CPU loops) by timing every callback the loop
runs.  The mechanism:

- **Timed callbacks.**  :func:`enable` patches
  ``asyncio.events.Handle._run`` (``TimerHandle`` inherits it) with a
  wrapper that stamps a per-thread *slot* — ``thread id → (t0,
  handle)`` — around the original dispatch.  Any callback whose wall
  time exceeds the **budget** (default 0.25 s; ``--stall-budget`` /
  ``$HBBFT_TPU_STALLCHECK_BUDGET``) becomes a :class:`StallReport`.
- **Mid-stall stack capture.**  A blocked loop cannot report on
  itself, so a watchdog daemon thread samples
  ``sys._current_frames()`` at budget/4 cadence; when a slot has been
  occupied past the budget it snapshots that thread's Python stack.
  The report therefore shows *where inside the callback* the time went
  (the ``os.fsync``, the pairing loop), not just which callback was
  slow — rendered as the violation's flow, like a lint rule's
  source→sink hops.
- **Attribution.**  The callback is named via its ``Task`` when the
  handle is a coroutine step (``Task.get_coro().__qualname__``) and
  via the callback's code object otherwise; the violation anchors at
  the innermost package frame of the captured stack (racecheck-style),
  falling back to the callback's definition site when the watchdog
  never got a sample.

Two front doors, mirroring :mod:`.racecheck`:

- ``pytest --stallcheck`` (``tests/conftest.py``): every test runs
  between :func:`enable` / :func:`disable`; reports accumulate into
  ``$HBBFT_TPU_STALLCHECK_OUT`` (JSONL) and fail the test.
- ``python -m hbbft_tpu.analysis --stallcheck <test-expr>``: runs the
  pytest expression in a subprocess and renders the collected reports
  like any other lint violation (rule ``stallcheck``).

The checker never changes scheduling: the wrapper delegates to the
original ``_run`` and only ever *observes*.  Known gaps, by design:
a callback that blocks for less than the budget is invisible (tune the
budget down for latency hunting); a stall inside a C extension that
never releases the GIL pins the watchdog too, so the sample lands as
soon as the GIL frees — elapsed time is still measured correctly from
the slot's ``t0``.
"""

from __future__ import annotations

import asyncio.events
import json
import os
import sys
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from .core import Violation

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
_PKG_ROOT = os.path.join(_REPO_ROOT, "hbbft_tpu")
_SELF = os.path.abspath(__file__)

OUT_ENV = "HBBFT_TPU_STALLCHECK_OUT"
BUDGET_ENV = "HBBFT_TPU_STALLCHECK_BUDGET"
DEFAULT_BUDGET_S = 0.25

# captured stacks keep at most this many frames (innermost last)
_MAX_FRAMES = 25


def _relpath(filename: str) -> str:
    path = os.path.abspath(filename)
    if path.startswith(_PKG_ROOT + os.sep):
        return os.path.relpath(path, _PKG_ROOT)
    if path.startswith(_REPO_ROOT + os.sep):
        return os.path.relpath(path, _REPO_ROOT)
    return os.path.basename(path)


def _in_package(filename: str) -> bool:
    path = os.path.abspath(filename)
    return path.startswith(_PKG_ROOT + os.sep) and path != _SELF


@dataclass
class StallReport:
    """One event-loop stall: a callback that held the loop past the
    budget."""

    callback: str
    path: str
    line: int
    elapsed_ms: float
    budget_ms: float
    # outermost-first (relpath, line, qualname) hops from the watchdog's
    # mid-stall sample; empty when the stall finished between samples
    stack: Tuple[Tuple[str, int, str], ...] = ()

    def message(self) -> str:
        where = (
            " (stack sampled mid-stall below)"
            if self.stack
            else " (finished between watchdog samples; anchor is the "
            "callback's definition)"
        )
        return (
            f"event-loop callback {self.callback} blocked the loop for "
            f"{self.elapsed_ms:.1f} ms (budget {self.budget_ms:.0f} ms) — "
            "every socket, timer, and peer link on this loop stalled with "
            f"it; offload the slow work with run_in_executor/to_thread"
            f"{where}"
        )

    def as_dict(self) -> Dict[str, Any]:
        return {
            "callback": self.callback,
            "path": self.path,
            "line": self.line,
            "elapsed_ms": self.elapsed_ms,
            "budget_ms": self.budget_ms,
            "stack": [list(h) for h in self.stack],
            "message": self.message(),
        }

    def as_violation(self) -> Violation:
        return Violation(
            rule="stallcheck",
            path=self.path,
            line=self.line,
            col=0,
            message=self.message(),
            flow=tuple(
                (p, ln, f"in {qual}()") for p, ln, qual in self.stack
            ),
        )


def _describe_callback(handle: Any) -> Tuple[str, str, int]:
    """(label, relpath, line) for a handle's callback — the coroutine's
    qualname when this is a Task step, the function's otherwise."""
    cb = getattr(handle, "_callback", None)
    owner = getattr(cb, "__self__", None)
    if isinstance(owner, asyncio.Task):
        try:
            coro = owner.get_coro()
            code = getattr(coro, "cr_code", None)
            qual = getattr(coro, "__qualname__", None) or "<coroutine>"
            if code is not None:
                return (
                    f"Task step {qual}()",
                    _relpath(code.co_filename),
                    code.co_firstlineno,
                )
            return f"Task step {qual}()", "<unknown>", 0
        except Exception:
            return "Task step <coroutine>", "<unknown>", 0
    func = cb
    while hasattr(func, "func"):  # functools.partial chains
        func = func.func
    code = getattr(func, "__code__", None)
    qual = getattr(func, "__qualname__", None) or repr(cb)
    if code is not None:
        return (
            f"{qual}()",
            _relpath(code.co_filename),
            code.co_firstlineno,
        )
    return f"{qual}()", "<unknown>", 0


def _snapshot(frame: Any) -> Tuple[Tuple[str, int, str], ...]:
    """Outermost-first (relpath, line, qualname) hops of a live frame
    stack, this module's own frames excluded."""
    hops: List[Tuple[str, int, str]] = []
    f = frame
    while f is not None and len(hops) < _MAX_FRAMES:
        fn = f.f_code.co_filename
        if os.path.abspath(fn) != _SELF:
            hops.append((_relpath(fn), f.f_lineno, f.f_code.co_name))
        f = f.f_back
    hops.reverse()
    return tuple(hops)


class StallChecker:
    """The slot bookkeeping + the ``Handle._run`` patch + the watchdog.

    Usable standalone (``chk = StallChecker(0.05); chk.install()``) or
    process-wide via the module-level :func:`enable`/:func:`disable`
    pair."""

    def __init__(self, budget_s: Optional[float] = None) -> None:
        if budget_s is None:
            budget_s = float(os.environ.get(BUDGET_ENV, DEFAULT_BUDGET_S))
        self.budget_s = max(1e-4, float(budget_s))
        self.reports: List[StallReport] = []
        self._mu = threading.Lock()
        self._seen: set = set()  # (path, line) dedupe
        # thread id -> (t0, handle) while that thread runs a callback
        self._slots: Dict[int, Tuple[float, Any]] = {}
        # thread id -> (handle, sampled stack) from the watchdog
        self._stacks: Dict[int, Tuple[Any, Tuple[Tuple[str, int, str], ...]]] = {}
        self._orig_run: Optional[Any] = None
        self._stop = threading.Event()
        self._watchdog: Optional[threading.Thread] = None

    # -- the Handle._run patch ------------------------------------------------

    def install(self) -> None:
        assert self._orig_run is None
        orig = asyncio.events.Handle._run
        self._orig_run = orig
        checker = self

        def _timed_run(handle: Any) -> Any:
            tid = threading.get_ident()
            t0 = time.perf_counter()
            checker._slots[tid] = (t0, handle)
            try:
                return orig(handle)
            finally:
                checker._slots.pop(tid, None)
                elapsed = time.perf_counter() - t0
                stack = checker._take_stack(tid, handle)
                if elapsed >= checker.budget_s:
                    checker._report(handle, elapsed, stack)

        asyncio.events.Handle._run = _timed_run
        self._stop.clear()
        self._watchdog = threading.Thread(
            target=self._watch, name="hbbft-stallcheck", daemon=True
        )
        self._watchdog.start()

    def uninstall(self) -> None:
        if self._orig_run is not None:
            asyncio.events.Handle._run = self._orig_run
            self._orig_run = None
        self._stop.set()
        if self._watchdog is not None:
            self._watchdog.join(timeout=2.0)
            self._watchdog = None
        self._slots.clear()
        self._stacks.clear()

    # -- the watchdog -----------------------------------------------------------

    def _watch(self) -> None:
        period = self.budget_s / 4.0
        while not self._stop.wait(period):
            if not self._slots:
                continue
            now = time.perf_counter()
            frames = sys._current_frames()
            for tid, (t0, handle) in list(self._slots.items()):
                if now - t0 < self.budget_s:
                    continue
                f = frames.get(tid)
                if f is not None:
                    stack = _snapshot(f)
                    with self._mu:
                        self._stacks[tid] = (handle, stack)

    def _take_stack(
        self, tid: int, handle: Any
    ) -> Tuple[Tuple[str, int, str], ...]:
        with self._mu:
            stashed = self._stacks.pop(tid, None)
        if stashed is not None and stashed[0] is handle:
            return stashed[1]
        return ()

    # -- reporting ----------------------------------------------------------------

    def _report(
        self,
        handle: Any,
        elapsed: float,
        stack: Tuple[Tuple[str, int, str], ...],
    ) -> None:
        label, path, line = _describe_callback(handle)
        # anchor at the innermost package frame of the sampled stack —
        # the actual blocking site — when we have one
        for p, ln, _qual in reversed(stack):
            cand = os.path.join(_PKG_ROOT, p)
            if os.path.isfile(cand) and _in_package(cand):
                path, line = p, ln
                break
        with self._mu:
            key = (path, line)
            if key in self._seen:
                return
            self._seen.add(key)
            self.reports.append(
                StallReport(
                    callback=label,
                    path=path,
                    line=line,
                    elapsed_ms=elapsed * 1000.0,
                    budget_ms=self.budget_s * 1000.0,
                    stack=stack,
                )
            )


# ---------------------------------------------------------------------------
# Process-wide switchboard (refcounted: nested enables share one checker)
# ---------------------------------------------------------------------------

_ACTIVE: Optional[StallChecker] = None
_DEPTH = 0
_SWITCH_LOCK = threading.Lock()


def active() -> Optional[StallChecker]:
    return _ACTIVE


def enable(budget_s: Optional[float] = None) -> StallChecker:
    """Install the process-wide checker (idempotent/refcounted).  The
    first enable's budget wins for the whole window."""
    global _ACTIVE, _DEPTH
    with _SWITCH_LOCK:
        if _ACTIVE is None:
            chk = StallChecker(budget_s)
            chk.install()
            _ACTIVE = chk
            _DEPTH = 0
        _DEPTH += 1
        return _ACTIVE


def disable() -> List[StallReport]:
    """Drop one enable; on the last one, restore ``Handle._run``, stop
    the watchdog, append the collected reports to
    ``$HBBFT_TPU_STALLCHECK_OUT`` (JSONL) when set, and return them."""
    global _ACTIVE, _DEPTH
    with _SWITCH_LOCK:
        if _ACTIVE is None:
            return []
        _DEPTH -= 1
        if _DEPTH > 0:
            return list(_ACTIVE.reports)
        chk = _ACTIVE
        _ACTIVE = None
    chk.uninstall()
    out = os.environ.get(OUT_ENV)
    if out and chk.reports:
        with open(out, "a") as fh:
            for r in chk.reports:
                fh.write(json.dumps(r.as_dict(), sort_keys=True) + "\n")
    return list(chk.reports)


def load_reports(path: str) -> List[StallReport]:
    """Parse a ``$HBBFT_TPU_STALLCHECK_OUT`` JSONL file back into
    reports (the CLI renders them as violations)."""
    reports: List[StallReport] = []
    try:
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                d = json.loads(line)
                reports.append(
                    StallReport(
                        callback=d["callback"],
                        path=d["path"],
                        line=int(d["line"]),
                        elapsed_ms=float(d["elapsed_ms"]),
                        budget_ms=float(d["budget_ms"]),
                        stack=tuple(
                            (h[0], int(h[1]), h[2])
                            for h in d.get("stack", ())
                        ),
                    )
                )
    except FileNotFoundError:
        pass
    return reports
