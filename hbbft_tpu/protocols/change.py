"""Validator-set change actions and their status.

Reference: ``src/dynamic_honey_badger/change.rs``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

from ..core.serialize import wire


class Change:
    """Base: a node change action (add or remove a validator)."""

    def candidate(self) -> Optional[Any]:
        return None


@wire("ChangeAdd")
@dataclasses.dataclass(frozen=True)
class Add(Change):
    """Add a node; the public key is used (only) for key generation."""

    node_id: Any
    pub_key: Any

    def candidate(self):
        return self.node_id


@wire("ChangeRemove")
@dataclasses.dataclass(frozen=True)
class Remove(Change):
    node_id: Any


class ChangeState:
    """Whether a change is pending, in progress, or completed."""


@wire("CsNone")
@dataclasses.dataclass(frozen=True)
class NoChange(ChangeState):
    pass


@wire("CsInProgress")
@dataclasses.dataclass(frozen=True)
class InProgress(ChangeState):
    change: Change


@wire("CsComplete")
@dataclasses.dataclass(frozen=True)
class Complete(ChangeState):
    change: Change
