"""Asynchronous Common Subset (ACS) — N broadcasts + N agreements.

Reference: ``src/common_subset.rs`` (344 LoC).  Runs one Reliable
Broadcast and one Binary Agreement per validator (the per-proposer
instance-parallelism axis, SURVEY §2.5.1 — the TPU backend vmaps crypto
across these N lanes).  Logic:

- own input → our Broadcast instance;
- Broadcast_j delivers ⇒ input ``true`` to Agreement_j (if still open);
- once N−f Agreements decided ``true`` ⇒ input ``false`` to the rest;
- when all N Agreements have decided and every yes-voted broadcast has
  delivered, output ``{proposer: value}`` for the yes set.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

from ..core.algorithm import DistAlgorithm, HbbftError
from ..core.fault import FaultKind
from ..core.network_info import NetworkInfo
from ..core.serialize import wire
from ..core.step import Step
from .agreement import Agreement, AgreementMessage
from .broadcast import Broadcast


@wire("CsBc")
@dataclasses.dataclass(frozen=True)
class CsBroadcast:
    proposer_id: Any
    msg: Any


@wire("CsAba")
@dataclasses.dataclass(frozen=True)
class CsAgreement:
    proposer_id: Any
    msg: AgreementMessage


class CommonSubsetError(HbbftError):
    pass


class CommonSubset(DistAlgorithm):
    def __init__(self, netinfo: NetworkInfo, session_id: int):
        self.netinfo = netinfo
        self.session_id = session_id
        self.broadcast_instances: Dict[Any, Broadcast] = {
            pid: Broadcast(netinfo, pid) for pid in netinfo.all_ids
        }
        self.agreement_instances: Dict[Any, Agreement] = {
            pid: Agreement(netinfo, session_id, pid)
            for pid in netinfo.all_ids
        }
        self.broadcast_results: Dict[Any, bytes] = {}
        self.agreement_results: Dict[Any, bool] = {}
        self.decided = False

    # -- DistAlgorithm -----------------------------------------------------

    def handle_input(self, value: bytes) -> Step:
        if not self.netinfo.is_validator:
            return Step()
        return self._process_broadcast(
            self.netinfo.our_id, lambda bc: bc.handle_input(value)
        )

    def handle_message(self, sender_id, message) -> Step:
        if isinstance(message, CsBroadcast):
            # the wire can carry an unhashable proposer_id (e.g. a list),
            # which would TypeError the membership test
            try:
                known = message.proposer_id in self.broadcast_instances
            except TypeError:
                return Step.from_fault(sender_id, FaultKind.INVALID_MESSAGE)
            if not known:
                return Step.from_fault(
                    sender_id, FaultKind.UNEXPECTED_PROPOSER
                )
            return self._process_broadcast(
                message.proposer_id,
                lambda bc: bc.handle_message(sender_id, message.msg),
            )
        if isinstance(message, CsAgreement):
            try:
                known = message.proposer_id in self.agreement_instances
            except TypeError:
                return Step.from_fault(sender_id, FaultKind.INVALID_MESSAGE)
            if not known:
                return Step.from_fault(
                    sender_id, FaultKind.UNEXPECTED_PROPOSER
                )
            return self._process_agreement(
                message.proposer_id,
                lambda ag: ag.handle_message(sender_id, message.msg),
            )
        return Step.from_fault(sender_id, FaultKind.INVALID_MESSAGE)

    def terminated(self) -> bool:
        return all(
            ag.terminated() for ag in self.agreement_instances.values()
        )

    def our_id(self):
        return self.netinfo.our_id

    def received_proposals(self) -> int:
        return len(self.broadcast_results)

    # -- internals ---------------------------------------------------------

    def _process_broadcast(self, proposer_id, fn) -> Step:
        step: Step = Step()
        bc = self.broadcast_instances[proposer_id]
        output = step.extend_with(
            fn(bc), lambda m: CsBroadcast(proposer_id, m)
        )
        if not output:
            return step
        self.broadcast_results[proposer_id] = output[0]

        def set_input(ag: Agreement):
            if ag.accepts_input():
                return ag.handle_input(True)
            return Step()

        step.extend(self._process_agreement(proposer_id, set_input))
        return step

    def _process_agreement(self, proposer_id, fn) -> Step:
        step: Step = Step()
        ag = self.agreement_instances[proposer_id]
        if ag.terminated():
            return step
        output = step.extend_with(
            fn(ag), lambda m: CsAgreement(proposer_id, m)
        )
        if not output:
            return step
        if proposer_id in self.agreement_results:
            raise CommonSubsetError("multiple agreement results")
        value = output[0]
        self.agreement_results[proposer_id] = value

        if value and self._count_true() == self.netinfo.num_correct:
            # N − f yes votes: input false into every open agreement
            # (reference ``common_subset.rs:271-289``)
            for pid in self.netinfo.all_ids:
                other = self.agreement_instances[pid]
                if other.accepts_input():
                    outs = step.extend_with(
                        other.handle_input(False),
                        lambda m, pid=pid: CsAgreement(pid, m),
                    )
                    for out in outs:
                        if pid in self.agreement_results:
                            raise CommonSubsetError(
                                "multiple agreement results"
                            )
                        self.agreement_results[pid] = out
        result = self._try_agreement_completion()
        if result is not None:
            step.output.append(result)
        return step

    def _count_true(self) -> int:
        return sum(1 for v in self.agreement_results.values() if v)

    def _try_agreement_completion(self):
        if self.decided or self._count_true() < self.netinfo.num_correct:
            return None
        if len(self.agreement_results) < self.netinfo.num_nodes:
            return None
        delivered_1 = {
            pid for pid, v in self.agreement_results.items() if v
        }
        # broadcast_results is keyed in arrival order; emit the decided
        # set in canonical proposer order so the output dict (and the
        # ciphertext-decrypt walk it seeds) is schedule-independent
        results = {
            pid: self.broadcast_results[pid]
            for pid in sorted(delivered_1, key=repr)
            if pid in self.broadcast_results
        }
        if len(results) == len(delivered_1):
            self.decided = True
            return results
        return None
