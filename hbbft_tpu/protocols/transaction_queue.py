"""Pending-transaction queue with random batch sampling.

Reference: ``src/transaction_queue.rs`` (35 LoC).  ``choose`` samples
``amount`` transactions uniformly from the first ``batch_size`` queued —
random disjoint-ish per-node contributions give expected O(1) duplicate
redundancy across proposers (``queueing_honey_badger.rs:13-23``).
"""

from __future__ import annotations

import collections
import itertools
from typing import Deque, Iterable, List


class TransactionQueue:
    def __init__(self, txs: Iterable = ()):  # FIFO of pending transactions
        self.queue: Deque = collections.deque(txs)

    def push(self, tx) -> None:
        self.queue.append(tx)

    def remove_all(self, txs: Iterable) -> None:
        """Drop every committed transaction from the queue in one pass.

        Builds the committed set once — O(n + m) with hashable
        transactions instead of the O(n·m) scan this used to be, which
        dominated the per-epoch commit path at gateway load.  Batches
        may carry unhashable foreign transactions injected by other
        proposers; those fall back to list membership rather than
        raising TypeError out of the commit path."""
        committed = list(txs)
        try:
            lookup = set(committed)
            self.queue = collections.deque(
                tx for tx in self.queue if tx not in lookup
            )
            return
        except TypeError:
            pass  # unhashable tx in the batch or the queue
        self.queue = collections.deque(
            tx for tx in self.queue if tx not in committed
        )

    def choose(self, amount: int, batch_size: int, rng) -> List:
        """Random sample of ``amount`` from the first ``batch_size``
        entries; the queue is unchanged.  (``islice`` — indexing a
        deque is O(distance from an end), so per-index access made
        large batch sizes quadratic.)"""
        head = list(itertools.islice(self.queue, min(batch_size, len(self.queue))))
        if len(head) <= amount:
            return head
        return rng.sample(head, amount)

    def __len__(self) -> int:
        return len(self.queue)
