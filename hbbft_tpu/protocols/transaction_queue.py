"""Pending-transaction queue with random batch sampling.

Reference: ``src/transaction_queue.rs`` (35 LoC).  ``choose`` samples
``amount`` transactions uniformly from the first ``batch_size`` queued —
random disjoint-ish per-node contributions give expected O(1) duplicate
redundancy across proposers (``queueing_honey_badger.rs:13-23``).
"""

from __future__ import annotations

import collections
import itertools
from typing import Deque, Iterable, List


class TransactionQueue:
    def __init__(self, txs: Iterable = ()):  # FIFO of pending transactions
        self.queue: Deque = collections.deque(txs)

    def push(self, tx) -> None:
        self.queue.append(tx)

    def remove_all(self, txs: Iterable) -> None:
        tx_set = set(txs)
        self.queue = collections.deque(
            tx for tx in self.queue if tx not in tx_set
        )

    def choose(self, amount: int, batch_size: int, rng) -> List:
        """Random sample of ``amount`` from the first ``batch_size``
        entries; the queue is unchanged.  (``islice`` — indexing a
        deque is O(distance from an end), so per-index access made
        large batch sizes quadratic.)"""
        head = list(itertools.islice(self.queue, min(batch_size, len(self.queue))))
        if len(head) <= amount:
            return head
        return rng.sample(head, amount)

    def __len__(self) -> int:
        return len(self.queue)
