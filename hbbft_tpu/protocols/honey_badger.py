"""HoneyBadger — epochs of threshold-encrypted Common Subset.

Reference: ``src/honey_badger/`` (504 + ~250 LoC).  Per epoch, every
validator serializes its contribution, encrypts it to the master
threshold key (censorship resistance: the adversary must commit to the
batch before seeing any contents, ``honey_badger.rs:101-122``), and
inputs the ciphertext into that epoch's ``CommonSubset``.  When the
subset is decided, each node multicasts a decryption share per accepted
proposer (N² shares per epoch network-wide — the single hottest crypto
surface, and the primary batched-TPU-kernel target, BASELINE config 4);
at > f verified shares a contribution is decrypted, and when all
accepted contributions decrypt, the epoch's ``Batch`` is output.

Deviations from the reference (deliberate, documented):
- messages for any epoch inside the ``[epoch, epoch+max_future_epochs]``
  window are handled immediately (the reference at this commit handles
  only ``epoch == current`` and silently drops within-window future
  messages, ``honey_badger.rs:68-77`` — a liveness hazard fixed in later
  upstream versions); beyond-window messages are queued, past ones
  dropped.
- ``reveal_mode="ordered"`` splits commit into two observable events
  (arXiv:2407.12172: threshold decryption is the residual critical-path
  cost).  **Ordered-commit**: the moment the common subset decides, the
  ciphertext batch is sequence-numbered and digest-pinned in an
  :class:`OrderedBatch` output, and the next epoch's ACS starts
  immediately.  **Reveal**: the plaintext :class:`Batch` follows
  asynchronously once enough decryption shares arrive.  Censorship
  resistance only needs order fixed *before* decryption — shares for
  epoch ``e`` still go out only after ``e``'s subset output is fixed
  (the ``no-early-decrypt`` lint pins this) — so deferring the reveal
  changes no adversarial power.  Reveal lag is bounded by
  ``max_outstanding_reveals``: at the bound, ordering stalls until the
  oldest pending epoch reveals (backpressure), keeping memory and lag
  finite under share-withholding peers.
"""

from __future__ import annotations

import dataclasses
import os
import random
from typing import Any, Dict, List, Optional, Set

from ..core.algorithm import DistAlgorithm, UnknownSenderError
from ..core.fault import FaultKind, FaultLog
from ..core.network_info import NetworkInfo
from ..core.serialize import SerializationError, dumps, loads, wire
from ..core.step import Step
from ..crypto.hashing import sha256
from ..obs import recorder as _obs
from .common_subset import CommonSubset

# Bounded future-message queue (state-transfer PR).  Beyond-window
# messages used to queue without limit — a flooding peer could grow
# ``incoming_queue`` arbitrarily with epochs far in the future.  Now
# queueing is capped per sender and per horizon; what exceeds either
# cap is counted (``hb.future_dropped``), emitted (``hb_future_drop``)
# and the repeat offender attributed every ``_FUTURE_FAULT_EVERY``
# drops, so a flooder is visible instead of invisible.
_FUTURE_HORIZON = 64  # queue at most this many epochs past the window
_FUTURE_MAX_PER_SENDER = 64  # queued future messages per sender
_FUTURE_FAULT_EVERY = 32  # attribute every Nth drop per sender


@wire("HbBatch")
@dataclasses.dataclass(frozen=True)
class Batch:
    """One epoch's output: the agreed, decrypted contributions
    (reference ``batch.rs:7-10``)."""

    epoch: int
    contributions: Dict[Any, Any]

    def tx_iter(self):
        for _, contrib in sorted(self.contributions.items(), key=lambda kv: str(kv[0])):
            yield from contrib

    def __len__(self) -> int:
        return sum(len(c) for c in self.contributions.values())

    def is_empty(self) -> bool:
        return all(len(c) == 0 for c in self.contributions.values())


@wire("HbOrderedBatch")
@dataclasses.dataclass(frozen=True)
class OrderedBatch:
    """The ordered-commit record (``reveal_mode="ordered"``): emitted
    the moment epoch ``epoch``'s common subset decides.  ``seq`` is the
    node-local monotonic commit sequence number, ``digest`` pins the
    agreed ciphertext batch (canonical serialization, so every correct
    node derives the same digest), ``proposers`` the accepted subset.
    The plaintext :class:`Batch` for the same epoch follows once
    decryption shares arrive."""

    epoch: int
    seq: int
    digest: bytes
    proposers: Any  # tuple of accepted proposer ids, canonical order


def ordered_batch_digest(epoch: int, ciphertexts: Dict[Any, Any]) -> bytes:
    """The digest an :class:`OrderedBatch` pins: a hash over the epoch
    and the canonical serialization of each accepted ciphertext, in
    proposer order.  Deterministic across nodes — the common subset
    fixed exactly these bytes."""
    parts = [dumps(epoch)]
    for pid in sorted(ciphertexts, key=str):
        parts.append(dumps(pid))
        parts.append(dumps(ciphertexts[pid]))
    return sha256(b"hbbft_tpu ordered batch v1" + b"".join(parts))


def default_reveal_mode() -> str:
    """Process-wide default: ``HBBFT_TPU_ORDERED_COMMIT=1`` flips every
    builder-constructed instance to order-then-reveal."""
    return (
        "ordered"
        if os.environ.get("HBBFT_TPU_ORDERED_COMMIT") == "1"
        else "inline"
    )


@wire("HbCs")
@dataclasses.dataclass(frozen=True)
class HbCommonSubset:
    msg: Any


@wire("HbDec")
@dataclasses.dataclass(frozen=True)
class HbDecryptionShare:
    proposer_id: Any
    share: Any


@wire("HbMsg")
@dataclasses.dataclass(frozen=True)
class HoneyBadgerMessage:
    epoch: int
    content: Any


class HoneyBadger(DistAlgorithm):
    """An instance of the Honey Badger BFT consensus algorithm."""

    def __init__(
        self,
        netinfo: NetworkInfo,
        max_future_epochs: int = 3,
        rng: Optional[random.Random] = None,
        speculative: bool = False,
        reveal_mode: Optional[str] = None,
        max_outstanding_reveals: int = 4,
    ):
        self.netinfo = netinfo
        self.epoch = 0
        self.has_input_flag = False
        self.common_subsets: Dict[int, CommonSubset] = {}
        self.max_future_epochs = max_future_epochs
        self.incoming_queue: Dict[int, List] = {}
        # epoch -> proposer -> sender -> share
        self.received_shares: Dict[int, Dict[Any, Dict[Any, Any]]] = {}
        # epoch -> proposer -> decrypted contribution bytes
        self.decrypted_contributions: Dict[int, Dict[Any, bytes]] = {}
        # epoch -> proposer -> ciphertext
        self.ciphertexts: Dict[int, Dict[Any, Any]] = {}
        # order-then-reveal (see module doc): "inline" reproduces the
        # reference (decrypt before the batch outputs); "ordered" emits
        # an OrderedBatch at ACS completion and reveals asynchronously.
        if reveal_mode is None:
            reveal_mode = default_reveal_mode()
        if reveal_mode not in ("inline", "ordered"):
            raise ValueError(f"unknown reveal_mode {reveal_mode!r}")
        self.reveal_mode = reveal_mode
        self.max_outstanding_reveals = max(1, int(max_outstanding_reveals))
        # epoch -> ordered seq, for ordered-but-unrevealed epochs; their
        # ciphertexts/received_shares stay pinned until the reveal
        self._pending_reveals: Dict[int, int] = {}
        self._ordered_seq = 0
        # speculative combine-first decryption (arXiv:2407.12172):
        # store shares unverified, combine the lowest f+1 at decrypt
        # time and validate the combined result once; per-share
        # verification runs only as the mismatch fallback (fault
        # attribution unchanged — see _try_decrypt_speculative).
        # Faults found by that deferred fallback accumulate here until
        # the next Step leaves this instance.
        self.speculative = speculative
        self._spec_hits = 0
        self._spec_misses = 0
        self._pending_faults = FaultLog()
        # future-queue accounting (bounded-memory long runs): how many
        # messages each sender has queued beyond the window, and how
        # many we have dropped on them (for periodic attribution)
        self._future_queued: Dict[Any, int] = {}
        self._future_drops: Dict[Any, int] = {}
        # deterministic per-node default (badgerlint: determinism) —
        # replayable and co-simulation-stable; the seed folds in our
        # secret key so the ciphertext randomness stays unpredictable
        self.rng = rng if rng is not None else netinfo.default_rng("honey_badger")

    # -- DistAlgorithm -----------------------------------------------------

    def handle_input(self, contribution) -> Step:
        return self.propose(contribution)

    def handle_message(self, sender_id, message) -> Step:
        if not self.netinfo.is_node_validator(sender_id):
            raise UnknownSenderError(f"unknown sender {sender_id!r}")
        if not isinstance(message, HoneyBadgerMessage):
            return Step.from_fault(sender_id, FaultKind.INVALID_MESSAGE)
        epoch = message.epoch
        # a deserialized message can carry anything in the epoch slot;
        # comparing/queueing a non-int would raise instead of faulting
        if not isinstance(epoch, int) or isinstance(epoch, bool):
            return Step.from_fault(sender_id, FaultKind.INVALID_MESSAGE)
        if epoch > self.epoch + self.max_future_epochs:
            queued = self._future_queued.get(sender_id, 0)
            if (
                epoch > self.epoch + self.max_future_epochs + _FUTURE_HORIZON
                or queued >= _FUTURE_MAX_PER_SENDER
            ):
                return self._drop_future(sender_id, epoch)
            self._future_queued[sender_id] = queued + 1
            self.incoming_queue.setdefault(epoch, []).append(
                (sender_id, message.content)
            )
            return Step()
        if epoch < self.epoch:
            if epoch in self._pending_reveals:
                # ordered-but-unrevealed epoch: late decryption shares
                # (and subset stragglers) must still flow to the reveal
                return self._handle_message_content(
                    sender_id, epoch, message.content
                )
            return Step()  # obsolete
        return self._handle_message_content(sender_id, epoch, message.content)

    def _drop_future(self, sender_id, epoch: int) -> Step:
        """A future-epoch message we will not queue: count it, surface
        it, and attribute the sender on every Nth drop (one drop can be
        clock skew; a stream of them is a flood)."""
        drops = self._future_drops.get(sender_id, 0) + 1
        self._future_drops[sender_id] = drops
        rec = _obs.ACTIVE
        if rec is not None:
            rec.count("hb.future_dropped")
            rec.event(
                "hb_future_drop",
                node=str(self.netinfo.our_id),
                epoch=epoch,
                drops=drops,
            )
        if drops % _FUTURE_FAULT_EVERY == 0:
            return Step.from_fault(sender_id, FaultKind.EPOCH_OUT_OF_RANGE)
        return Step()

    def _dec_future(self, sender_id) -> None:
        n = self._future_queued.get(sender_id, 0)
        if n <= 1:
            self._future_queued.pop(sender_id, None)
        else:
            self._future_queued[sender_id] = n - 1

    def terminated(self) -> bool:
        return False  # HoneyBadger runs forever

    def our_id(self):
        return self.netinfo.our_id

    # -- proposing ---------------------------------------------------------

    def propose(self, contribution) -> Step:
        if not self.netinfo.is_validator:
            return Step()
        epoch = self.epoch
        cs = self._common_subset(epoch)
        ser = dumps(contribution)
        ciphertext = self.netinfo.public_key_set.public_key().encrypt(
            ser, self.rng
        )
        self.has_input_flag = True
        cs_step = cs.handle_input(dumps(ciphertext))
        return self._process_output(cs_step, epoch)

    def has_input(self) -> bool:
        return not self.netinfo.is_validator or self.has_input_flag

    def received_proposals(self) -> int:
        cs = self.common_subsets.get(self.epoch)
        return cs.received_proposals() if cs else 0

    # -- message handling --------------------------------------------------

    def _common_subset(self, epoch: int) -> CommonSubset:
        cs = self.common_subsets.get(epoch)
        if cs is None:
            cs = CommonSubset(self.netinfo, epoch)
            self.common_subsets[epoch] = cs
        return cs

    def _handle_message_content(self, sender_id, epoch, content) -> Step:
        if isinstance(content, HbCommonSubset):
            return self._handle_common_subset_message(
                sender_id, epoch, content.msg
            )
        if isinstance(content, HbDecryptionShare):
            return self._handle_decryption_share_message(
                sender_id, epoch, content.proposer_id, content.share
            )
        return Step.from_fault(sender_id, FaultKind.INVALID_MESSAGE)

    def _handle_common_subset_message(self, sender_id, epoch, cs_msg) -> Step:
        if epoch < self.epoch and epoch not in self.common_subsets:
            return Step()  # epoch already terminated
        cs = self._common_subset(epoch)
        cs_step = cs.handle_message(sender_id, cs_msg)
        step = self._process_output(cs_step, epoch)
        self._remove_terminated()
        return step

    def _handle_decryption_share_message(
        self, sender_id, epoch, proposer_id, share
    ) -> Step:
        # an unhashable proposer id (e.g. a decoded list) could never key
        # received_shares/ciphertexts — reject before any dict lookup
        try:
            known = self.netinfo.is_node_validator(proposer_id)
        except TypeError:
            return Step.from_fault(sender_id, FaultKind.INVALID_MESSAGE)
        if not known:
            return Step.from_fault(sender_id, FaultKind.UNEXPECTED_PROPOSER)
        ciphertext = self.ciphertexts.get(epoch, {}).get(proposer_id)
        if ciphertext is not None and not self.speculative:
            if not self._verify_decryption_share(
                sender_id, share, ciphertext
            ):
                return Step.from_fault(
                    sender_id, FaultKind.INVALID_DECRYPTION_SHARE
                )
        # store (unverified if the ciphertext is not yet known; it will be
        # checked in _verify_pending_decryption_shares)
        self.received_shares.setdefault(epoch, {}).setdefault(
            proposer_id, {}
        )[sender_id] = share
        if epoch == self.epoch or epoch in self._pending_reveals:
            return self._try_output_batches()
        return Step()

    def _verify_decryption_share(self, sender_id, share, ciphertext) -> bool:
        pk = self.netinfo.public_key_share(sender_id)
        if pk is None:
            return False
        try:
            return self.netinfo.ops.verify_dec_share(pk, share, ciphertext)
        except Exception:
            return False

    # -- decryption + batch output ----------------------------------------

    def _process_output(self, cs_step, epoch: int) -> Step:
        step: Step = Step()
        cs_outputs = step.extend_with(
            cs_step,
            lambda m: HoneyBadgerMessage(epoch, HbCommonSubset(m)),
        )
        for cs_output in cs_outputs[:1]:
            step.extend(self._send_decryption_shares(cs_output, epoch))
        return step

    def _send_decryption_shares(self, cs_output, epoch: int) -> Step:
        step: Step = Step()
        ciphertexts: Dict[Any, Any] = {}
        for proposer_id in sorted(cs_output):
            ser_ct = cs_output[proposer_id]
            try:
                ciphertext = loads(ser_ct)
            except (SerializationError, Exception):
                step.add_fault(proposer_id, FaultKind.INVALID_CIPHERTEXT)
                continue
            try:
                valid = ciphertext.verify()
            except Exception:
                valid = False
            if not valid:
                step.add_fault(proposer_id, FaultKind.INVALID_CIPHERTEXT)
                continue
            if not self.speculative:
                incorrect, faults = self._verify_pending_decryption_shares(
                    proposer_id, ciphertext, epoch
                )
                self._remove_incorrect_decryption_shares(
                    proposer_id, incorrect, epoch
                )
                step.fault_log.merge(faults)
            if self.netinfo.is_validator:
                step.extend(
                    self._send_decryption_share(proposer_id, ciphertext, epoch)
                )
            ciphertexts[proposer_id] = ciphertext
        self.ciphertexts[epoch] = ciphertexts
        rec = _obs.ACTIVE
        if rec is not None:
            # the ACS→decrypt boundary of the fleet commit timeline:
            # the subset is agreed, decryption shares go out now
            rec.event(
                "acs_done",
                node=str(self.netinfo.our_id),
                epoch=epoch,
                proposers=len(ciphertexts),
            )
        if epoch == self.epoch:
            step.extend(self._try_output_batches())
        return step

    def _send_decryption_share(self, proposer_id, ciphertext, epoch) -> Step:
        share = self.netinfo.secret_key_share.decrypt_share_no_verify(
            ciphertext
        )
        self.received_shares.setdefault(epoch, {}).setdefault(
            proposer_id, {}
        )[self.netinfo.our_id] = share
        step: Step = Step()
        step.send_all(
            HoneyBadgerMessage(epoch, HbDecryptionShare(proposer_id, share))
        )
        return step

    def _verify_pending_decryption_shares(
        self, proposer_id, ciphertext, epoch
    ):
        from ..core.fault import Fault, FaultLog

        incorrect: Set = set()
        faults = FaultLog()
        shares = self.received_shares.get(epoch, {}).get(proposer_id, {})
        # dict order is share-arrival order, which differs per schedule
        # — walk canonically so the fault log (and every downstream
        # message emission) is schedule-independent
        for sender_id, share in sorted(
            shares.items(), key=lambda kv: repr(kv[0])
        ):
            if not self._verify_decryption_share(
                sender_id, share, ciphertext
            ):
                faults.add(sender_id, FaultKind.INVALID_DECRYPTION_SHARE)
                incorrect.add(sender_id)
        return incorrect, faults

    def _remove_incorrect_decryption_shares(
        self, proposer_id, incorrect, epoch
    ) -> None:
        shares = self.received_shares.get(epoch, {}).get(proposer_id, {})
        for sender_id in sorted(incorrect, key=repr):
            shares.pop(sender_id, None)

    def _try_output_batches(self) -> Step:
        step: Step = Step()
        while True:
            progressed = False
            while True:
                new_step = self._try_output_batch()
                if new_step is None:
                    break
                progressed = True
                step.extend(new_step)
            if self.reveal_mode == "ordered":
                revealed = self._try_reveal_batches(step)
                if revealed:
                    # a completed reveal may have unstalled backpressured
                    # ordering — retry the commit loop
                    progressed = True
                    continue
            break
        if not self._pending_faults.is_empty():
            # faults found by the speculative-combine fallback: surface
            # them on whichever Step leaves the instance next (the eager
            # path reports at share arrival; the set is identical)
            step.fault_log.merge(self._pending_faults)
            self._pending_faults = FaultLog()
        return step

    def _try_output_batch(self) -> Optional[Step]:
        cts = self.ciphertexts.get(self.epoch)
        if cts is None:
            return None
        if self.reveal_mode == "ordered":
            return self._try_ordered_commit(cts)
        if not all(
            self._try_decrypt_proposer_contribution(pid, self.epoch)
            for pid in sorted(cts)
        ):
            return None
        step = self._assemble_batch(self.epoch)
        step.extend(self._update_epoch())
        return step

    def _try_ordered_commit(self, cts) -> Optional[Step]:
        """Ordered-commit: seal the epoch's agreed ciphertext batch the
        moment ACS output lands and advance to the next epoch without
        waiting for decryption.  Per-epoch state stays pinned until the
        reveal.  At ``max_outstanding_reveals`` pending epochs, ordering
        stalls (backpressure) until the oldest reveal completes."""
        if len(self._pending_reveals) >= self.max_outstanding_reveals:
            rec = _obs.ACTIVE
            if rec is not None:
                rec.count("hb.order_stalled")
            return None
        epoch = self.epoch
        seq = self._ordered_seq
        self._ordered_seq += 1
        self._pending_reveals[epoch] = seq
        step: Step = Step()
        step.output.append(
            OrderedBatch(
                epoch=epoch,
                seq=seq,
                digest=ordered_batch_digest(epoch, cts),
                proposers=tuple(sorted(cts, key=str)),
            )
        )
        rec = _obs.ACTIVE
        if rec is not None:
            rec.event(
                "ordered_commit",
                node=str(self.netinfo.our_id),
                epoch=epoch,
                seq=seq,
                outstanding=len(self._pending_reveals),
                proposers=len(cts),
            )
        step.extend(self._update_epoch(retain=True))
        return step

    def _try_reveal_batches(self, step: Step) -> bool:
        """Reveal pending ordered epochs, oldest first, extending
        ``step`` in place.  Reveals are delivered in epoch order (the
        ordered log's order), so the loop stops at the first epoch
        still short of decryption shares.  Returns whether any epoch
        revealed."""
        revealed = False
        for epoch in sorted(self._pending_reveals):
            new_step = self._try_reveal_batch(epoch)
            if new_step is None:
                break
            revealed = True
            step.extend(new_step)
        return revealed

    def _try_reveal_batch(self, epoch: int) -> Optional[Step]:
        cts = self.ciphertexts.get(epoch)
        if cts is None:  # state-transfer jumped past it
            self._pending_reveals.pop(epoch, None)
            return None
        if not all(
            self._try_decrypt_proposer_contribution(pid, epoch)
            for pid in sorted(cts)
        ):
            return None
        step = self._assemble_batch(epoch)
        self._pending_reveals.pop(epoch, None)
        self.ciphertexts.pop(epoch, None)
        self.received_shares.pop(epoch, None)
        rec = _obs.ACTIVE
        if rec is not None:
            rec.event(
                "reveal_lag",
                epoch=epoch,
                lag_epochs=self.epoch - epoch,
                node=str(self.netinfo.our_id),
                outstanding=len(self._pending_reveals),
            )
        return step

    def _assemble_batch(self, epoch: int) -> Step:
        """Deserialize epoch ``epoch``'s decrypted contributions into
        its plaintext :class:`Batch` (shared by the inline commit and
        the deferred reveal — byte-identical output by construction)."""
        step: Step = Step()
        contributions: Dict[Any, Any] = {}
        decrypted = self.decrypted_contributions.pop(epoch, {})
        for proposer_id, ser in sorted(decrypted.items(), key=lambda kv: str(kv[0])):
            try:
                contributions[proposer_id] = loads(ser)
            except (SerializationError, Exception):
                step.add_fault(
                    proposer_id, FaultKind.BATCH_DESERIALIZATION_FAILED
                )
        batch = Batch(epoch, contributions)
        step.output.append(batch)
        if self.speculative:
            rec = _obs.ACTIVE
            if rec is not None:
                rec.event(
                    "spec_combine",
                    hits=self._spec_hits,
                    misses=self._spec_misses,
                    epoch=batch.epoch,
                )
            self._spec_hits = 0
            self._spec_misses = 0
        return step

    def _try_decrypt_proposer_contribution(self, proposer_id, epoch) -> bool:
        if proposer_id in self.decrypted_contributions.get(epoch, {}):
            return True
        shares = self.received_shares.get(epoch, {}).get(proposer_id)
        if not shares or len(shares) <= self.netinfo.num_faulty:
            return False
        ciphertext = self.ciphertexts[epoch][proposer_id]
        if self.speculative:
            return self._try_decrypt_speculative(
                proposer_id, ciphertext, shares, epoch
            )
        shares_by_idx = {
            self.netinfo.node_index(nid): share
            for nid, share in shares.items()
        }
        try:
            contrib = self.netinfo.public_key_set.combine_decryption_shares(
                shares_by_idx, ciphertext
            )
            self.decrypted_contributions.setdefault(epoch, {})[
                proposer_id
            ] = contrib
        except Exception:
            # All shares were verified; failure here means the proposer's
            # ciphertext was malformed in a way verify() missed.  The
            # contribution is skipped (reference logs and continues,
            # ``honey_badger.rs:344-346``).
            pass
        return True

    def _try_decrypt_speculative(
        self, proposer_id, ciphertext, shares, epoch
    ) -> bool:
        """Combine-first decryption: combine the lowest f+1 received
        shares *unverified* and validate the combined result with one
        check.  Only on mismatch (a bad share inside the window) run
        the exact eager ``_verify_pending_decryption_shares`` sweep —
        the same senders are faulted with ``INVALID_DECRYPTION_SHARE``
        (deferred to the next outgoing Step), the bad shares are
        dropped, and the combine retries from what survives."""
        combine = getattr(
            self.netinfo.public_key_set,
            "combine_and_check_decryption_shares",
            None,
        )
        if combine is not None:
            shares_by_idx = {
                self.netinfo.node_index(nid): share
                for nid, share in shares.items()
            }
            sub_idxs = sorted(shares_by_idx)[: self.netinfo.num_faulty + 1]
            try:
                contrib = combine(
                    {i: shares_by_idx[i] for i in sub_idxs}, ciphertext
                )
            except Exception:
                contrib = None
            if contrib is not None:
                self._spec_hits += 1
                self.decrypted_contributions.setdefault(epoch, {})[
                    proposer_id
                ] = contrib
                return True
            self._spec_misses += 1
        # fallback: the eager path, verbatim — verify every pending
        # share, fault + drop the bad ones, recombine from the rest
        incorrect, faults = self._verify_pending_decryption_shares(
            proposer_id, ciphertext, epoch
        )
        self._remove_incorrect_decryption_shares(
            proposer_id, incorrect, epoch
        )
        self._pending_faults.merge(faults)
        shares = self.received_shares.get(epoch, {}).get(proposer_id)
        if not shares or len(shares) <= self.netinfo.num_faulty:
            return False
        shares_by_idx = {
            self.netinfo.node_index(nid): share
            for nid, share in shares.items()
        }
        try:
            contrib = self.netinfo.public_key_set.combine_decryption_shares(
                shares_by_idx, ciphertext
            )
            self.decrypted_contributions.setdefault(epoch, {})[
                proposer_id
            ] = contrib
        except Exception:
            pass  # see the eager branch above
        return True

    def _update_epoch(self, retain: bool = False) -> Step:
        if not retain:
            self.ciphertexts.pop(self.epoch, None)
            self.received_shares.pop(self.epoch, None)
        self.epoch += 1
        self.has_input_flag = False
        max_epoch = self.epoch + self.max_future_epochs
        step: Step = Step()
        for sender_id, content in self.incoming_queue.pop(max_epoch, []):
            self._dec_future(sender_id)
            step.extend(
                self._handle_message_content(sender_id, max_epoch, content)
            )
        step.extend(self._try_output_batches())
        return step

    def _remove_terminated(self) -> None:
        for epoch in [
            e
            for e, cs in self.common_subsets.items()
            if e < self.epoch and cs.terminated()
        ]:
            del self.common_subsets[epoch]

    # -- state transfer + bounded-memory GC --------------------------------

    def fast_forward(self, upto_epoch: int, batches: List[Any]) -> Step:
        """Install a quorum-verified snapshot: output the transferred
        batches for epochs ``[self.epoch, upto_epoch]`` and jump to
        ``upto_epoch + 1``, exactly as if this node had decided those
        epochs itself.  The caller (``recover/transfer.py``) has
        already digest-verified the batches against f+1 peers.

        In-flight per-epoch state for the skipped window is discarded
        (those epochs are decided — the batch IS the decision); queued
        future messages that land inside the new window are
        re-dispatched, ones behind it are dropped."""
        if upto_epoch < self.epoch:
            return Step()
        step: Step = Step()
        by_epoch: Dict[int, Any] = {}
        for b in batches:
            ep = getattr(b, "epoch", None)
            if (
                isinstance(b, Batch)
                and isinstance(ep, int)
                and not isinstance(ep, bool)
                and self.epoch <= ep <= upto_epoch
            ):
                by_epoch[ep] = b
        for ep in sorted(by_epoch):
            step.output.append(by_epoch[ep])
        for d in (self.common_subsets, self.received_shares, self.ciphertexts):
            for ep in [e for e in d if e <= upto_epoch]:
                del d[ep]
        self.decrypted_contributions = {}
        # ordered-but-unrevealed epochs inside the jump are decided by
        # the transferred batches — the pending reveals are moot
        for ep in [e for e in self._pending_reveals if e <= upto_epoch]:
            del self._pending_reveals[ep]
        self._pending_faults = FaultLog()
        self.epoch = upto_epoch + 1
        self.has_input_flag = False
        # re-dispatch queued messages now inside the window; drop the
        # ones the jump made obsolete
        window_hi = self.epoch + self.max_future_epochs
        for ep in sorted([e for e in self.incoming_queue if e <= window_hi]):
            for sender_id, content in self.incoming_queue.pop(ep, []):
                self._dec_future(sender_id)
                if ep >= self.epoch:
                    step.extend(
                        self._handle_message_content(sender_id, ep, content)
                    )
        step.extend(self._try_output_batches())
        return step

    def gc_epochs(self) -> int:
        """Prune per-epoch state for epochs before the current one —
        the driver calls this after each durable checkpoint, so a
        long-running node's dicts stay bounded by the live window.
        (``_remove_terminated`` already drops *terminated* past subset
        instances; this also reclaims ones wedged by a faulty peer.)"""
        dropped = 0
        for d in (self.common_subsets, self.received_shares, self.ciphertexts):
            for ep in [
                e
                for e in d
                if e < self.epoch and e not in self._pending_reveals
            ]:
                del d[ep]
                dropped += 1
        for ep in [e for e in self.incoming_queue if e < self.epoch]:
            for sender_id, _ in self.incoming_queue.pop(ep):
                self._dec_future(sender_id)
            dropped += 1
        return dropped


class HoneyBadgerBuilder:
    """Builder mirroring the reference's configuration surface
    (``honey_badger/builder.rs:13-57``)."""

    def __init__(self, netinfo: NetworkInfo):
        self.netinfo = netinfo
        self._max_future_epochs = 3
        self._rng: Optional[random.Random] = None
        self._speculative = False
        self._reveal_mode: Optional[str] = None  # None → env default
        self._max_outstanding_reveals = 4

    def max_future_epochs(self, value: int) -> "HoneyBadgerBuilder":
        self._max_future_epochs = value
        return self

    def rng(self, rng: random.Random) -> "HoneyBadgerBuilder":
        self._rng = rng
        return self

    def speculative(self, value: bool = True) -> "HoneyBadgerBuilder":
        """Combine-first decryption: one combined check per
        contribution instead of per-share verifies (fallback on
        mismatch keeps fault attribution)."""
        self._speculative = value
        return self

    def reveal_mode(self, value: str) -> "HoneyBadgerBuilder":
        """``"inline"`` (reference semantics) or ``"ordered"``
        (order-then-reveal: OrderedBatch at ACS completion, plaintext
        Batch asynchronously)."""
        self._reveal_mode = value
        return self

    def ordered(self, value: bool = True) -> "HoneyBadgerBuilder":
        """Shorthand for ``reveal_mode("ordered")``."""
        self._reveal_mode = "ordered" if value else "inline"
        return self

    def max_outstanding_reveals(self, value: int) -> "HoneyBadgerBuilder":
        """Backpressure bound for ``reveal_mode="ordered"``: ordering
        stalls once this many epochs are ordered but unrevealed."""
        self._max_outstanding_reveals = value
        return self

    def build(self) -> HoneyBadger:
        return HoneyBadger(
            self.netinfo,
            max_future_epochs=self._max_future_epochs,
            rng=self._rng,
            speculative=self._speculative,
            reveal_mode=self._reveal_mode,
            max_outstanding_reveals=self._max_outstanding_reveals,
        )
