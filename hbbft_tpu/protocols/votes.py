"""Era-scoped vote buffer/counter for validator-set changes.

Reference: ``src/dynamic_honey_badger/votes.rs`` (303 LoC).  Each
validator holds one active vote; a later vote (higher ``num``)
supersedes it.  Pending votes ride inside HoneyBadger contributions and
only *committed* (batch-ordered) votes are counted, so every node counts
the identical sequence.  A change wins at > f committed votes.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Iterator, Optional

from ..core.fault import FaultKind, FaultLog
from ..core.network_info import NetworkInfo
from ..core.serialize import dumps, wire
from .change import Change


@wire("Vote")
@dataclasses.dataclass(frozen=True)
class Vote:
    change: Change
    era: int  # epoch at which the current era began
    num: int  # higher numbers supersede earlier votes by the same voter


@wire("SignedVote")
@dataclasses.dataclass(frozen=True)
class SignedVote:
    vote: Vote
    voter: Any
    sig: Any

    @property
    def era(self) -> int:
        return self.vote.era


def _well_formed(signed_vote) -> bool:
    """Structural sanity for a vote received off the wire: the decoder
    will happily build a ``SignedVote`` whose fields are the wrong types
    (non-``Vote`` vote, unhashable voter/change, non-int era/num), and
    any of those would raise out of the dict/comparison operations the
    counters run — a remote-triggered crash instead of a ``Fault``."""
    if not isinstance(signed_vote, SignedVote):
        return False
    vote = signed_vote.vote
    if not isinstance(vote, Vote) or not isinstance(vote.change, Change):
        return False
    if not isinstance(vote.era, int) or isinstance(vote.era, bool):
        return False
    if not isinstance(vote.num, int) or isinstance(vote.num, bool):
        return False
    try:
        hash(signed_vote.voter)
        hash(vote.change)
    except TypeError:
        return False
    return True


class VoteCounter:
    def __init__(self, netinfo: NetworkInfo, era: int):
        self.netinfo = netinfo
        self.era = era
        self.pending: Dict[Any, SignedVote] = {}
        self.committed: Dict[Any, Vote] = {}

    # -- signing + buffering ----------------------------------------------

    def sign_vote_for(self, change: Change) -> SignedVote:
        """Create, sign and buffer our own vote (reference ``:45-61``)."""
        voter = self.netinfo.our_id
        prev = self.pending.get(voter)
        vote = Vote(change, self.era, prev.vote.num + 1 if prev else 0)
        sig = self.netinfo.secret_key.sign(dumps(vote))
        signed = SignedVote(vote, voter, sig)
        self.pending[voter] = signed
        return signed

    def add_pending_vote(self, sender_id, signed_vote: SignedVote) -> FaultLog:
        """Buffer a vote received off-chain (reference ``:64-85``)."""
        faults = FaultLog()
        if not _well_formed(signed_vote):
            faults.add(sender_id, FaultKind.INVALID_VOTE_SIGNATURE)
            return faults
        prev = self.pending.get(signed_vote.voter)
        if signed_vote.vote.era != self.era or (
            prev is not None and prev.vote.num >= signed_vote.vote.num
        ):
            return faults  # obsolete or already present
        if not self._validate(signed_vote):
            faults.add(sender_id, FaultKind.INVALID_VOTE_SIGNATURE)
            return faults
        self.pending[signed_vote.voter] = signed_vote
        return faults

    def pending_votes(self) -> Iterator[SignedVote]:
        """Pending votes newer than their voter's committed vote."""
        for voter in sorted(self.pending, key=str):
            sv = self.pending[voter]
            committed = self.committed.get(voter)
            if committed is None or committed.num < sv.vote.num:
                yield sv

    # -- committed votes ---------------------------------------------------

    def add_committed_votes(self, proposer_id, signed_votes) -> FaultLog:
        faults = FaultLog()
        for sv in signed_votes:
            faults.merge(self.add_committed_vote(proposer_id, sv))
        return faults

    def add_committed_vote(self, proposer_id, signed_vote: SignedVote) -> FaultLog:
        faults = FaultLog()
        if not _well_formed(signed_vote):
            faults.add(proposer_id, FaultKind.INVALID_VOTE_SIGNATURE)
            return faults
        prev = self.committed.get(signed_vote.voter)
        if prev is not None and prev.num >= signed_vote.vote.num:
            return faults  # obsolete
        if signed_vote.vote.era != self.era or not self._validate(signed_vote):
            faults.add(proposer_id, FaultKind.INVALID_VOTE_SIGNATURE)
            return faults
        self.committed[signed_vote.voter] = signed_vote.vote
        return faults

    def compute_winner(self) -> Optional[Change]:
        """The change with > f committed votes, if any (reference
        ``:137-148``)."""
        counts: Dict[Change, int] = {}
        for voter in sorted(self.committed, key=str):
            change = self.committed[voter].change
            counts[change] = counts.get(change, 0) + 1
            if counts[change] > self.netinfo.num_faulty:
                return change
        return None

    def _validate(self, signed_vote: SignedVote) -> bool:
        pk = self.netinfo.public_key(signed_vote.voter)
        if pk is None:
            return False
        try:
            return pk.verify(signed_vote.sig, dumps(signed_vote.vote))
        except Exception:
            return False
