"""Binary Byzantine Agreement (ABA) — Mostéfaoui-Moumen-Raynal style.

Reference: ``src/agreement/`` (agreement.rs 408 + mod.rs 172 LoC).
Each node inputs a bool; all correct nodes output the same bool, which
was input by at least one correct node.  Per epoch:

1. SBV-Broadcast the estimate (BVal/Aux thresholds f+1 / 2f+1 / N−f);
2. before a *real* coin epoch, a ``Conf`` round fixes candidate values
   (finishes at N−f Confs ⊆ bin_values, ``agreement.rs:355-376``);
3. obtain the coin: epochs ≡ 0 mod 3 → true, ≡ 1 mod 3 → false,
   ≡ 2 mod 3 → threshold-signature CommonCoin (``agreement.rs:314-328``
   — the fixed schedule makes the common case coin-free);
4. unique candidate == coin ⇒ decide and broadcast ``Term``; otherwise
   next epoch with estimate = candidate or coin.

``Term(b)`` counts as BVal+Aux+Conf for all future epochs and enables
expedited termination at f+1 Terms (``agreement.rs:213-228``).  Future-
epoch messages are queued; expired non-Term messages are dropped
(``can_expire``, ``mod.rs:119-125``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

from ..core.algorithm import DistAlgorithm, HbbftError
from ..core.fault import FaultKind
from ..core.network_info import NetworkInfo
from ..core.serialize import wire
from ..core.fault import log as _log
from ..core.step import Step
from .bool_set import BoolMultimap, BoolSet
from .common_coin import CommonCoin, CommonCoinMessage, make_nonce
from .sbv_broadcast import Aux, BVal, SbvBroadcast


# -- messages ---------------------------------------------------------------


@wire("AbaSbv")
@dataclasses.dataclass(frozen=True)
class SbvContent:
    msg: Any  # BVal | Aux


@wire("AbaConf")
@dataclasses.dataclass(frozen=True)
class ConfContent:
    values: BoolSet


@wire("AbaTerm")
@dataclasses.dataclass(frozen=True)
class TermContent:
    value: bool


@wire("AbaCoin")
@dataclasses.dataclass(frozen=True)
class CoinContent:
    msg: CommonCoinMessage


@wire("AbaMsg")
@dataclasses.dataclass(frozen=True)
class AgreementMessage:
    epoch: int
    content: Any

    def can_expire(self) -> bool:
        return not isinstance(self.content, TermContent)


class InputNotAccepted(HbbftError):
    pass


class UnknownProposer(HbbftError):
    pass


# -- coin state -------------------------------------------------------------


class _CoinState:
    """Fixed coin value, or an in-progress CommonCoin instance."""

    __slots__ = ("decided", "coin")

    def __init__(self, decided: Optional[bool], coin: Optional[CommonCoin]):
        self.decided = decided
        self.coin = coin

    @classmethod
    def fixed(cls, value: bool) -> "_CoinState":
        return cls(value, None)

    @classmethod
    def in_progress(cls, coin: CommonCoin) -> "_CoinState":
        return cls(None, coin)

    def value(self) -> Optional[bool]:
        return self.decided


class Agreement(DistAlgorithm):
    def __init__(self, netinfo: NetworkInfo, session_id: int, proposer_id):
        if not netinfo.is_node_validator(proposer_id):
            raise UnknownProposer(f"unknown proposer {proposer_id!r}")
        self.netinfo = netinfo
        self.session_id = session_id
        self.proposer_id = proposer_id
        self.epoch = 0
        self.sbv_broadcast = SbvBroadcast(netinfo)
        self.received_conf: Dict[Any, BoolSet] = {}
        self.received_term = BoolMultimap()
        self.estimated: Optional[bool] = None
        self.decision: Optional[bool] = None
        self.incoming_queue: Dict[int, List[Tuple[Any, Any]]] = {}
        self.conf_values: Optional[BoolSet] = None
        self.coin_state = _CoinState.fixed(True)  # epoch 0 coin is true

    # -- DistAlgorithm -----------------------------------------------------

    def handle_input(self, value: bool) -> Step:
        if self.epoch != 0 or self.estimated is not None:
            raise InputNotAccepted("input only accepted in epoch 0")
        self.estimated = bool(value)
        sbvb_step = self.sbv_broadcast.handle_input(bool(value))
        return self._handle_sbvb_step(sbvb_step)

    def accepts_input(self) -> bool:
        return self.epoch == 0 and self.estimated is None

    def handle_message(self, sender_id, message) -> Step:
        if not isinstance(message, AgreementMessage):
            return Step.from_fault(sender_id, FaultKind.INVALID_MESSAGE)
        # epoch arrives off the wire: a non-int would raise in the
        # comparisons / queue keying below instead of being attributed
        if not isinstance(message.epoch, int) or isinstance(message.epoch, bool):
            return Step.from_fault(sender_id, FaultKind.INVALID_MESSAGE)
        if self.decision is not None or (
            message.epoch < self.epoch and message.can_expire()
        ):
            return Step()  # obsolete
        if message.epoch > self.epoch:
            # queue for later (reference ``agreement.rs:95-99``)
            self.incoming_queue.setdefault(message.epoch, []).append(
                (sender_id, message.content)
            )
            return Step()
        return self._handle_content(sender_id, message.content)

    def terminated(self) -> bool:
        return self.decision is not None

    def our_id(self):
        return self.netinfo.our_id

    # -- dispatch ----------------------------------------------------------

    def _handle_content(self, sender_id, content) -> Step:
        if isinstance(content, SbvContent):
            sbvb_step = self.sbv_broadcast.handle_message(
                sender_id, content.msg
            )
            return self._handle_sbvb_step(sbvb_step)
        if isinstance(content, ConfContent):
            if not isinstance(content.values, BoolSet):
                return Step.from_fault(sender_id, FaultKind.INVALID_MESSAGE)
            return self._handle_conf(sender_id, content.values)
        if isinstance(content, TermContent):
            if not isinstance(content.value, bool):
                return Step.from_fault(sender_id, FaultKind.INVALID_MESSAGE)
            return self._handle_term(sender_id, content.value)
        if isinstance(content, CoinContent):
            return self._handle_coin(sender_id, content.msg)
        return Step.from_fault(sender_id, FaultKind.INVALID_MESSAGE)

    def _handle_sbvb_step(self, sbvb_step) -> Step:
        step: Step = Step()
        epoch = self.epoch
        output = step.extend_with(
            sbvb_step,
            lambda m: AgreementMessage(epoch, SbvContent(m)),
        )
        if self.conf_values is not None:
            return step  # Conf round already started
        for aux_vals in output[:1]:
            if self.coin_state.decided is not None:
                self.conf_values = aux_vals
                step.extend(self._try_update_epoch())
            else:
                step.extend(self._send_conf(aux_vals))
        return step

    # -- Conf round --------------------------------------------------------

    def _handle_conf(self, sender_id, values: BoolSet) -> Step:
        if sender_id in self.received_conf:
            return Step.from_fault(sender_id, FaultKind.DUPLICATE_CONF)
        self.received_conf[sender_id] = values
        return self._try_finish_conf_round()

    def _send_conf(self, values: BoolSet) -> Step:
        if self.conf_values is not None:
            return Step()
        self.conf_values = values
        if not self.netinfo.is_validator:
            return self._try_finish_conf_round()
        return self._send(ConfContent(values))

    def _try_finish_conf_round(self) -> Step:
        if self.conf_values is None or self._count_conf() < self.netinfo.num_correct:
            return Step()
        if self.coin_state.coin is None:
            return Step()  # coin already decided
        coin_step = self.coin_state.coin.handle_input()
        step = self._on_coin_step(coin_step)
        step.extend(self._try_update_epoch())
        return step

    def _count_conf(self) -> int:
        bv = self.sbv_broadcast.bin_values
        return sum(
            1 for c in self.received_conf.values() if c.is_subset(bv)
        )

    # -- Term --------------------------------------------------------------

    def _handle_term(self, sender_id, b: bool) -> Step:
        if sender_id in self.received_term[b]:
            return Step.from_fault(sender_id, FaultKind.DUPLICATE_TERM)
        self.received_term[b].add(sender_id)
        if self.decision is not None:
            return Step()
        if len(self.received_term[b]) > self.netinfo.num_faulty:
            return self._decide(b)  # expedited termination
        # count as BVal + Aux + Conf
        sbvb_step = self.sbv_broadcast.handle_bval(sender_id, b)
        sbvb_step.extend(self.sbv_broadcast.handle_aux(sender_id, b))
        step = self._handle_sbvb_step(sbvb_step)
        step.extend(self._handle_conf(sender_id, BoolSet.single(b)))
        return step

    # -- coin --------------------------------------------------------------

    def _handle_coin(self, sender_id, msg: CommonCoinMessage) -> Step:
        if self.coin_state.coin is None:
            return Step()  # already decided
        coin_step = self.coin_state.coin.handle_message(sender_id, msg)
        return self._on_coin_step(coin_step)

    def _on_coin_step(self, coin_step) -> Step:
        step: Step = Step()
        epoch = self.epoch
        coin_output = step.extend_with(
            coin_step,
            lambda m: AgreementMessage(epoch, CoinContent(m)),
        )
        for coin in coin_output[:1]:
            self.coin_state = _CoinState.fixed(bool(coin))
            step.extend(self._try_update_epoch())
        return step

    def _coin_state_for_epoch(self) -> _CoinState:
        m = self.epoch % 3
        if m == 0:
            return _CoinState.fixed(True)
        if m == 1:
            return _CoinState.fixed(False)
        nonce = make_nonce(
            self.netinfo.invocation_id(),
            self.session_id,
            self.netinfo.node_index(self.proposer_id),
            self.epoch,
        )
        return _CoinState.in_progress(CommonCoin(self.netinfo, nonce))

    # -- epoch transitions -------------------------------------------------

    def _try_update_epoch(self) -> Step:
        if self.decision is not None:
            return Step()
        coin = self.coin_state.value()
        if coin is None:
            return Step()
        if self.conf_values is None:
            return Step()
        def_bin = self.conf_values.definite()
        if def_bin is not None and def_bin == coin:
            return self._decide(coin)
        return self._update_epoch(def_bin if def_bin is not None else coin)

    def _decide(self, b: bool) -> Step:
        if self.decision is not None:
            return Step()
        self.decision = b
        _log.debug(
            "%r: agreement on %r decided %s at epoch %d",
            self.netinfo.our_id, self.proposer_id, b, self.epoch,
        )
        step = Step.with_output(b)
        if self.netinfo.is_validator:
            step.send_all(AgreementMessage(self.epoch + 1, TermContent(b)))
        return step

    def _update_epoch(self, b: bool) -> Step:
        self.sbv_broadcast.clear(self.received_term)
        self.received_conf = {
            nid: BoolSet.single(v) for v, nid in self.received_term
        }
        self.conf_values = None
        self.epoch += 1
        self.coin_state = self._coin_state_for_epoch()
        self.estimated = b
        sbvb_step = self.sbv_broadcast.handle_input(b)
        step = self._handle_sbvb_step(sbvb_step)
        for sender_id, content in self.incoming_queue.pop(self.epoch, []):
            step.extend(self._handle_content(sender_id, content))
            if self.decision is not None:
                break
        return step

    # -- messaging ---------------------------------------------------------

    def _send(self, content) -> Step:
        if not self.netinfo.is_validator:
            return Step()
        step: Step = Step()
        step.send_all(AgreementMessage(self.epoch, content))
        step.extend(self._handle_content(self.netinfo.our_id, content))
        return step


def random_message(rng):
    """Garbage agreement message for fuzz adversaries (reference
    ``agreement/mod.rs:137-149``)."""
    epoch = rng.randrange(3)
    kind = rng.randrange(4)
    if kind == 0:
        inner = BVal(bool(rng.randrange(2))) if rng.randrange(2) else Aux(
            bool(rng.randrange(2))
        )
        return AgreementMessage(epoch, SbvContent(inner))
    if kind == 1:
        return AgreementMessage(epoch, ConfContent(BoolSet(rng.randrange(4))))
    if kind == 2:
        return AgreementMessage(epoch, TermContent(bool(rng.randrange(2))))
    from ..crypto.mock import MockSignatureShare

    share = MockSignatureShare(
        rng.randrange(2**256).to_bytes(32, "big"),
        rng.randrange(2**256).to_bytes(32, "big"),
    )
    return AgreementMessage(epoch, CoinContent(CommonCoinMessage(share)))
