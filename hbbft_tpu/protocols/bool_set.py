"""Tiny bool-set containers used by Binary Agreement.

Reference: ``src/agreement/bool_set.rs`` (2-bit set of booleans) and
``src/agreement/bool_multimap.rs`` (``bool → set-of-nodes`` map).
"""

from __future__ import annotations

from typing import Dict, Iterator, Optional, Set

from ..core.serialize import wire


@wire("BoolSet")
class BoolSet:
    """Subset of {False, True} encoded in two bits (NONE/FALSE/TRUE/BOTH)."""

    __slots__ = ("bits",)

    def __init__(self, bits: int = 0):
        if not 0 <= bits <= 3:
            raise ValueError("BoolSet bits out of range")
        self.bits = bits

    # constructors ---------------------------------------------------------

    @classmethod
    def none(cls) -> "BoolSet":
        return cls(0)

    @classmethod
    def both(cls) -> "BoolSet":
        return cls(3)

    @classmethod
    def single(cls, b: bool) -> "BoolSet":
        return cls(2 if b else 1)

    # operations -----------------------------------------------------------

    def insert(self, b: bool) -> bool:
        """Add ``b``; returns True if it was newly inserted."""
        bit = 2 if b else 1
        if self.bits & bit:
            return False
        self.bits |= bit
        return True

    def __contains__(self, b: bool) -> bool:
        return bool(self.bits & (2 if b else 1))

    def is_subset(self, other: "BoolSet") -> bool:
        return (self.bits & ~other.bits) == 0

    def definite(self) -> Optional[bool]:
        """The single contained value, if exactly one."""
        if self.bits == 1:
            return False
        if self.bits == 2:
            return True
        return None

    def __iter__(self) -> Iterator[bool]:
        if self.bits & 1:
            yield False
        if self.bits & 2:
            yield True

    def __len__(self) -> int:
        return bin(self.bits).count("1")

    def copy(self) -> "BoolSet":
        return BoolSet(self.bits)

    def __eq__(self, other) -> bool:
        return isinstance(other, BoolSet) and self.bits == other.bits

    def __hash__(self) -> int:
        return hash(("BoolSet", self.bits))

    def __repr__(self) -> str:
        return f"BoolSet({sorted(self)})"

    def _wire_fields(self):
        return (self.bits,)

    @classmethod
    def _from_wire(cls, bits):
        return cls(bits)


class BoolMultimap:
    """``bool → set of node ids`` (who sent BVal(b)/Aux(b))."""

    __slots__ = ("_sets",)

    def __init__(self):
        self._sets: Dict[bool, Set] = {False: set(), True: set()}

    def __getitem__(self, b: bool) -> Set:
        return self._sets[b]

    def __iter__(self):
        """Iterate (b, node_id) pairs, deterministically ordered."""
        for b in (False, True):
            for nid in sorted(self._sets[b]):
                yield b, nid

    def copy(self) -> "BoolMultimap":
        m = BoolMultimap()
        m._sets = {False: set(self._sets[False]), True: set(self._sets[True])}
        return m
