"""DynamicHoneyBadger — HoneyBadger with dynamic validator membership.

Reference: ``src/dynamic_honey_badger/`` (~1,240 LoC across 6 files).
Wraps an inner ``HoneyBadger`` whose contributions bundle the user's
data with signed votes and signed DKG messages
(``InternalContrib``, ``mod.rs:187-194``).  Votes and ``Part``/``Ack``
messages are committed *on-chain* — ordered by HoneyBadger batches —
before being counted or fed into ``SyncKeyGen``, which makes the
inherently synchronous DKG safe on an asynchronous network
(``sync_key_gen.rs:3-5``).

A change wins at f+1 committed votes → DKG (re)starts; DKG completion
swaps ``NetworkInfo`` and restarts the inner HoneyBadger in a new *era*
(``start_epoch``).  Each change-bearing batch carries a ``JoinPlan``
from which a fresh node can join as an observer at the next epoch
boundary (``batch.rs:87-99``, ``builder.rs:82-114``).
"""

from __future__ import annotations

import dataclasses
import random
from typing import Any, Dict, List, Optional, Tuple

from ..core.algorithm import DistAlgorithm, UnknownSenderError
from ..core.fault import FaultKind, FaultLog
from ..core.network_info import NetworkInfo
from ..core.serialize import dumps, wire
from ..core.step import Step
from .change import Add, Change, ChangeState, Complete, InProgress, NoChange, Remove
from .honey_badger import HoneyBadger
from .sync_key_gen import Ack, Part, SyncKeyGen
from .votes import SignedVote, VoteCounter


# -- inputs -----------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class UserInput:
    contribution: Any


@dataclasses.dataclass(frozen=True)
class ChangeInput:
    change: Change


# -- on-chain payloads -------------------------------------------------------


@wire("KgPart")
@dataclasses.dataclass(frozen=True)
class KgPart:
    part: Part


@wire("KgAck")
@dataclasses.dataclass(frozen=True)
class KgAck:
    ack: Ack


@wire("SignedKgMsg")
@dataclasses.dataclass(frozen=True)
class SignedKeyGenMsg:
    era: int
    node_id: Any
    kg_msg: Any  # KgPart | KgAck
    sig: Any


@wire("InternalContrib")
@dataclasses.dataclass(frozen=True)
class InternalContrib:
    contrib: Any
    key_gen_messages: Tuple
    votes: Tuple


# -- wire messages ----------------------------------------------------------


@wire("DhbHb")
@dataclasses.dataclass(frozen=True)
class DhbHoneyBadger:
    start_epoch: int
    msg: Any


@wire("DhbKeyGen")
@dataclasses.dataclass(frozen=True)
class DhbKeyGen:
    era: int
    kg_msg: Any
    sig: Any


@wire("DhbVote")
@dataclasses.dataclass(frozen=True)
class DhbSignedVote:
    signed_vote: SignedVote


def _message_era(message) -> Optional[int]:
    if isinstance(message, DhbHoneyBadger):
        era = message.start_epoch
    elif isinstance(message, DhbKeyGen):
        era = message.era
    elif isinstance(message, DhbSignedVote):
        era = getattr(message.signed_vote, "era", None)
    else:
        return None
    # off-wire fields can hold anything; a non-int era would raise in
    # the caller's comparisons — treat it as no era (invalid message)
    if not isinstance(era, int) or isinstance(era, bool):
        return None
    return era


# -- batch ------------------------------------------------------------------


@wire("JoinPlan")
@dataclasses.dataclass(frozen=True)
class JoinPlan:
    """Everything a fresh observer needs to join at an epoch boundary
    (reference ``mod.rs:136-145``)."""

    epoch: int
    change: ChangeState
    pub_key_set: Any
    pub_keys: Dict[Any, Any]


class DhbBatch:
    """One epoch's output incl. membership-change state (reference
    ``dynamic_honey_badger/batch.rs``)."""

    def __init__(self, epoch: int):
        self.epoch = epoch
        self.contributions: Dict[Any, Any] = {}
        self.change: ChangeState = NoChange()
        self.pub_netinfo: Optional[Tuple[Any, Dict[Any, Any]]] = None

    def set_change(self, change: ChangeState, netinfo: NetworkInfo) -> None:
        self.change = change
        if not isinstance(change, NoChange):
            self.pub_netinfo = (
                netinfo.public_key_set,
                netinfo.public_key_map,
            )

    def join_plan(self) -> Optional[JoinPlan]:
        if self.pub_netinfo is None:
            return None
        pk_set, pub_keys = self.pub_netinfo
        return JoinPlan(self.epoch + 1, self.change, pk_set, pub_keys)

    def tx_iter(self):
        for _, contrib in sorted(self.contributions.items(), key=lambda kv: str(kv[0])):
            yield from contrib

    def __len__(self) -> int:
        return sum(len(c) for c in self.contributions.values())

    def __repr__(self) -> str:
        return (
            f"DhbBatch(epoch={self.epoch}, n={len(self.contributions)}, "
            f"change={self.change!r})"
        )


# -- key generation state ----------------------------------------------------


class _KeyGenState:
    """Ongoing DKG + the change it applies to (reference
    ``mod.rs:147-181``)."""

    def __init__(self, key_gen: SyncKeyGen, change: Change):
        self.key_gen = key_gen
        self.change = change
        self.candidate_msg_count = 0

    def is_ready(self) -> bool:
        if not self.key_gen.is_ready():
            return False
        candidate = self.change.candidate()
        return candidate is None or self.key_gen.is_node_ready(candidate)

    def candidate_key(self, node_id):
        if isinstance(self.change, Add) and self.change.node_id == node_id:
            return self.change.pub_key
        return None


class DynamicHoneyBadger(DistAlgorithm):
    def __init__(
        self,
        netinfo: NetworkInfo,
        max_future_epochs: int = 3,
        rng: Optional[random.Random] = None,
        start_epoch: int = 0,
    ):
        self.netinfo = netinfo
        self.max_future_epochs = max_future_epochs
        # deterministic per-node default (badgerlint: determinism)
        self.rng = (
            rng if rng is not None else netinfo.default_rng("dynamic_honey_badger")
        )
        self.start_epoch = start_epoch
        self.vote_counter = VoteCounter(netinfo, start_epoch)
        self.key_gen_msg_buffer: List[SignedKeyGenMsg] = []
        self.honey_badger = HoneyBadger(
            netinfo, max_future_epochs=max_future_epochs, rng=self.rng
        )
        self.key_gen_state: Optional[_KeyGenState] = None
        self.incoming_queue: List[Tuple[Any, Any]] = []

    # -- DistAlgorithm -----------------------------------------------------

    def handle_input(self, input) -> Step:
        if isinstance(input, UserInput):
            return self.propose(input.contribution)
        if isinstance(input, ChangeInput):
            return self.vote_for(input.change)
        # bare contribution convenience
        return self.propose(input)

    def handle_message(self, sender_id, message) -> Step:
        era = _message_era(message)
        if era is None:
            return Step.from_fault(sender_id, FaultKind.INVALID_MESSAGE)
        if era < self.start_epoch:
            return Step()  # obsolete
        if era > self.start_epoch:
            self.incoming_queue.append((sender_id, message))
            return Step()
        if isinstance(message, DhbHoneyBadger):
            return self._handle_honey_badger_message(sender_id, message.msg)
        if isinstance(message, DhbKeyGen):
            faults = self._handle_key_gen_message(
                sender_id, message.kg_msg, message.sig
            )
            return Step.from_fault_log(faults)
        if isinstance(message, DhbSignedVote):
            faults = self.vote_counter.add_pending_vote(
                sender_id, message.signed_vote
            )
            return Step.from_fault_log(faults)
        return Step.from_fault(sender_id, FaultKind.INVALID_MESSAGE)

    def terminated(self) -> bool:
        return False

    def our_id(self):
        return self.netinfo.our_id

    # -- input paths -------------------------------------------------------

    def has_input(self) -> bool:
        return self.honey_badger.has_input()

    def propose(self, contrib) -> Step:
        internal = InternalContrib(
            contrib,
            tuple(self.key_gen_msg_buffer),
            tuple(self.vote_counter.pending_votes()),
        )
        hb_step = self.honey_badger.handle_input(internal)
        return self._process_output(hb_step)

    def vote_for(self, change: Change) -> Step:
        if not self.netinfo.is_validator:
            return Step()
        signed_vote = self.vote_counter.sign_vote_for(change)
        step: Step = Step()
        step.send_all(DhbSignedVote(signed_vote))
        return step

    def should_propose(self) -> bool:
        """Anti-stall rule (reference ``dynamic_honey_badger.rs:145-165``):
        propose even without content if a correct node wants to advance,
        or we have pending votes/DKG messages to commit."""
        if self.has_input():
            return False
        if self.honey_badger.received_proposals() > self.netinfo.num_faulty:
            return True
        if any(
            sv.voter == self.netinfo.our_id
            for sv in self.vote_counter.pending_votes()
        ):
            return True
        kgs = self.key_gen_state
        if kgs is None:
            return False
        candidate = kgs.change.candidate()
        return any(
            m.node_id == self.netinfo.our_id or m.node_id == candidate
            for m in self.key_gen_msg_buffer
        )

    # -- message handling --------------------------------------------------

    def _handle_honey_badger_message(self, sender_id, hb_msg) -> Step:
        if not self.netinfo.is_node_validator(sender_id):
            raise UnknownSenderError(f"unknown sender {sender_id!r}")
        hb_step = self.honey_badger.handle_message(sender_id, hb_msg)
        return self._process_output(hb_step)

    def _handle_key_gen_message(self, sender_id, kg_msg, sig) -> FaultLog:
        """Buffer a signed DKG message for on-chain commitment; it is
        only *handled* once output in a batch."""
        faults = FaultLog()
        if not self._verify_signature(sender_id, sig, kg_msg):
            faults.add(sender_id, FaultKind.INVALID_KEY_GEN_MESSAGE_SIGNATURE)
            return faults
        kgs = self.key_gen_state
        if kgs is None:
            faults.add(sender_id, FaultKind.UNEXPECTED_KEY_GEN_MESSAGE)
            return faults
        if sender_id == kgs.change.candidate():
            n = self.netinfo.num_nodes + 1
            if kgs.candidate_msg_count > n * n:
                faults.add(sender_id, FaultKind.KEY_GEN_MESSAGE_SPAM)
                return faults
            kgs.candidate_msg_count += 1
        self.key_gen_msg_buffer.append(
            SignedKeyGenMsg(self.start_epoch, sender_id, kg_msg, sig)
        )
        return faults

    # -- batch processing --------------------------------------------------

    def _process_output(self, hb_step) -> Step:
        step: Step = Step()
        start_epoch = self.start_epoch
        output = step.extend_with(
            hb_step, lambda m: DhbHoneyBadger(start_epoch, m)
        )
        for hb_batch in output:
            batch = DhbBatch(hb_batch.epoch + self.start_epoch)
            for nid in sorted(hb_batch.contributions, key=str):
                int_contrib = hb_batch.contributions[nid]
                if not isinstance(int_contrib, InternalContrib):
                    step.add_fault(
                        nid, FaultKind.BATCH_DESERIALIZATION_FAILED
                    )
                    continue
                step.fault_log.merge(
                    self.vote_counter.add_committed_votes(
                        nid, int_contrib.votes
                    )
                )
                batch.contributions[nid] = int_contrib.contrib
                committed = int_contrib.key_gen_messages
                self.key_gen_msg_buffer = [
                    m for m in self.key_gen_msg_buffer if m not in committed
                ]
                for skgm in int_contrib.key_gen_messages:
                    if not isinstance(skgm, SignedKeyGenMsg):
                        step.add_fault(nid, FaultKind.INVALID_MESSAGE)
                        continue
                    if skgm.era < self.start_epoch:
                        continue  # obsolete
                    if not self._verify_signature(
                        skgm.node_id, skgm.sig, skgm.kg_msg
                    ):
                        step.add_fault(
                            nid, FaultKind.INVALID_KEY_GEN_MESSAGE_SIGNATURE
                        )
                        continue
                    if isinstance(skgm.kg_msg, KgPart):
                        step.extend(
                            self._handle_part(skgm.node_id, skgm.kg_msg.part)
                        )
                    elif isinstance(skgm.kg_msg, KgAck):
                        step.fault_log.merge(
                            self._handle_ack(skgm.node_id, skgm.kg_msg.ack)
                        )
            kgs = self._take_ready_key_gen()
            if kgs is not None:
                # DKG complete: swap keys, restart inner HB in a new era
                self.netinfo = kgs.key_gen.into_network_info(
                    ops=self.netinfo.ops
                )
                self._restart_honey_badger(batch.epoch + 1)
                batch.set_change(Complete(kgs.change), self.netinfo)
            else:
                winner = self.vote_counter.compute_winner()
                if winner is not None:
                    step.extend(self._update_key_gen(batch.epoch + 1, winner))
                    batch.set_change(InProgress(winner), self.netinfo)
            step.output.append(batch)
        if start_epoch < self.start_epoch:
            queue, self.incoming_queue = self.incoming_queue, []
            for sender_id, msg in queue:
                step.extend(self.handle_message(sender_id, msg))
        return step

    # -- DKG lifecycle -----------------------------------------------------

    def _update_key_gen(self, epoch: int, change: Change) -> Step:
        if (
            self.key_gen_state is not None
            and self.key_gen_state.change == change
        ):
            return Step()  # same change: continue current DKG
        pub_keys = self.netinfo.public_key_map
        if isinstance(change, Remove):
            pub_keys.pop(change.node_id, None)
        elif isinstance(change, Add):
            pub_keys[change.node_id] = change.pub_key
        self._restart_honey_badger(epoch)
        threshold = (len(pub_keys) - 1) // 3
        key_gen = SyncKeyGen(
            self.netinfo.our_id,
            self.netinfo.secret_key,
            pub_keys,
            threshold,
            self.rng,
        )
        self.key_gen_state = _KeyGenState(key_gen, change)
        if key_gen.our_part is not None:
            return self._send_transaction(KgPart(key_gen.our_part))
        return Step()

    def _restart_honey_badger(self, epoch: int) -> None:
        self.start_epoch = epoch
        self.key_gen_msg_buffer = [
            m for m in self.key_gen_msg_buffer if m.era >= epoch
        ]
        self.vote_counter = VoteCounter(self.netinfo, epoch)
        self.honey_badger = HoneyBadger(
            self.netinfo,
            max_future_epochs=self.max_future_epochs,
            rng=self.rng,
        )

    def _handle_part(self, sender_id, part: Part) -> Step:
        kgs = self.key_gen_state
        if kgs is None:
            return Step()
        ack, faults = kgs.key_gen.handle_part(sender_id, part, self.rng)
        step = Step.from_fault_log(faults)
        if ack is not None:
            step.extend(self._send_transaction(KgAck(ack)))
        return step

    def _handle_ack(self, sender_id, ack: Ack) -> FaultLog:
        if self.key_gen_state is None:
            return FaultLog()
        return self.key_gen_state.key_gen.handle_ack(sender_id, ack)

    def _send_transaction(self, kg_msg) -> Step:
        """Sign, buffer and multicast a DKG message for on-chain
        commitment (reference ``:360-372``)."""
        sig = self.netinfo.secret_key.sign(dumps(kg_msg))
        step: Step = Step()
        if self.netinfo.is_validator:
            self.key_gen_msg_buffer.append(
                SignedKeyGenMsg(
                    self.start_epoch, self.netinfo.our_id, kg_msg, sig
                )
            )
        step.send_all(DhbKeyGen(self.start_epoch, kg_msg, sig))
        return step

    def _take_ready_key_gen(self) -> Optional[_KeyGenState]:
        kgs = self.key_gen_state
        if kgs is not None and kgs.is_ready():
            self.key_gen_state = None
            return kgs
        return None

    def _verify_signature(self, node_id, sig, kg_msg) -> bool:
        pk = self.netinfo.public_key(node_id)
        if pk is None and self.key_gen_state is not None:
            pk = self.key_gen_state.candidate_key(node_id)
        if pk is None:
            return False
        try:
            return pk.verify(sig, dumps(kg_msg))
        except Exception:
            return False


class DynamicHoneyBadgerBuilder:
    """Reference ``dynamic_honey_badger/builder.rs``: ``build``,
    ``build_first_node`` and ``build_joining``."""

    def __init__(self):
        self._max_future_epochs = 3
        self._rng: Optional[random.Random] = None

    def max_future_epochs(self, value: int) -> "DynamicHoneyBadgerBuilder":
        self._max_future_epochs = value
        return self

    def rng(self, rng: random.Random) -> "DynamicHoneyBadgerBuilder":
        self._rng = rng
        return self

    def build(self, netinfo: NetworkInfo) -> DynamicHoneyBadger:
        return DynamicHoneyBadger(
            netinfo,
            max_future_epochs=self._max_future_epochs,
            rng=self._rng,
        )

    def build_first_node(self, our_id, mock: bool = False) -> DynamicHoneyBadger:
        """Start a new network as its single validator."""
        from ..crypto import mock as M
        from ..crypto import threshold as T

        # fresh OS-entropy keys are REQUIRED here: this generates the
        # network's first secret key set, so a derivable seed would let
        # anyone reconstruct it  # lint: ok(determinism)
        rng = self._rng if self._rng is not None else random.Random()
        if mock:
            sk_set = M.MockSecretKeySet.random(0, rng)
            sk = M.MockSecretKey.random(rng)
        else:
            sk_set = T.SecretKeySet.random(0, rng)
            sk = T.SecretKey.random(rng)
        netinfo = NetworkInfo(
            our_id,
            sk_set.secret_key_share(0),
            sk,
            sk_set.public_keys(),
            {our_id: sk.public_key()},
        )
        return self.build(netinfo)

    def build_joining(
        self, our_id, secret_key, join_plan: JoinPlan, ops=None
    ) -> Tuple[DynamicHoneyBadger, Step]:
        """Join a running network as an observer from a ``JoinPlan``."""
        netinfo = NetworkInfo(
            our_id,
            None,
            secret_key,
            join_plan.pub_key_set,
            join_plan.pub_keys,
            ops=ops,
        )
        dhb = DynamicHoneyBadger(
            netinfo,
            max_future_epochs=self._max_future_epochs,
            rng=self._rng,
            start_epoch=join_plan.epoch,
        )
        step: Step = Step()
        if isinstance(join_plan.change, InProgress):
            step = dhb._update_key_gen(join_plan.epoch, join_plan.change.change)
        return dhb, step
