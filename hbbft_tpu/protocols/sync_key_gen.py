"""SyncKeyGen — dealerless distributed key generation (Pedersen-style).

Reference: ``src/sync_key_gen.rs`` (465 LoC).  Each validator deals a
random symmetric bivariate polynomial of degree t, publishing a G2
commitment and one encrypted row per node (``Part``); receivers check
their row against the commitment and answer with encrypted evaluations
(``Ack``); values are verified against the commitment
(``commit.evaluate(i, j) == val·P₂``, the exact check at
``sync_key_gen.rs:449``).  A Part is *complete* at 2t+1 Acks; the DKG is
*ready* when > t parts are complete; ``generate()`` sums the complete
parts' zero-row commitments and interpolates own column values (lowest
t+1 sender indices — the deterministic subset rule) into the secret
share.

The algorithm is synchronous — all nodes must handle the identical
message sequence — which is exactly what DynamicHoneyBadger guarantees
by committing Parts/Acks *on-chain* (``sync_key_gen.rs:3-5``).

TPU-first design notes: commitments live in G2 (public-key group); each
``Part`` additionally carries the dealer's master-secret commitment in
G1 (``master_g1``), pairing-checked against the G2 commitment, because
threshold *encryption* needs the master key in G1 (see
``crypto/threshold.py``).  A mock dealing path mirrors the message flow
for fast protocol tests.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Set, Tuple

from ..core.fault import FaultKind, FaultLog
from ..core.serialize import SerializationError, dumps, loads, wire
from ..crypto import fields as F
from ..crypto import mock as M
from ..crypto import threshold as T
from ..crypto.curve import G1, G1_GEN, G2_GEN
from ..crypto.hashing import sha256
from ..crypto.pairing import pairing_check
from ..crypto.poly import BivarCommitment, BivarPoly, Commitment, Poly, interpolate_at_zero


@wire("DkgPart")
@dataclasses.dataclass(frozen=True)
class Part:
    """Commitment + per-node encrypted rows (+ G1 master commitment)."""

    commit: Any  # BivarCommitment (real) | bytes commitment (mock)
    rows: Tuple  # encrypted row per node (real) | plain seed (mock)
    master_g1: Any  # G1 (real) | None (mock)


@wire("DkgAck")
@dataclasses.dataclass(frozen=True)
class Ack:
    proposer_idx: int
    values: Tuple  # encrypted value per node (real) | plain seed (mock)


class _ProposalState:
    """Tracks one dealer's sharing process (reference ``ProposalState``,
    ``sync_key_gen.rs:206-229``)."""

    def __init__(self, commit, master_g1):
        self.commit = commit
        self.master_g1 = master_g1
        self.values: Dict[int, int] = {}  # sender_idx+1 -> Fr value
        self.acks: Set[int] = set()
        self.mock_seed: Optional[bytes] = None

    def is_complete(self, threshold: int) -> bool:
        return len(self.acks) > 2 * threshold


class SyncKeyGen:
    """One DKG session over a fixed candidate validator set."""

    def __init__(self, our_id, sec_key, pub_keys: Dict[Any, Any], threshold: int, rng):
        """Returns the instance; the ``Part`` to multicast is in
        ``self.our_part`` (None for observers)."""
        self.our_id = our_id
        self.sec_key = sec_key
        self.pub_keys = dict(pub_keys)
        self.threshold = threshold
        self.node_ids = sorted(pub_keys)
        self.our_idx: Optional[int] = (
            self.node_ids.index(our_id) if our_id in pub_keys else None
        )
        self.parts: Dict[int, _ProposalState] = {}
        self.mock = isinstance(sec_key, M.MockSecretKey)
        self.our_part: Optional[Part] = None
        if self.our_idx is None:
            return  # observer: deals nothing
        if self.mock:
            seed = rng.randrange(2**256).to_bytes(32, "big")
            self.our_part = Part(sha256(b"DKGSEED" + seed), (seed,) * len(self.node_ids), None)
        else:
            bivar = BivarPoly.random(threshold, rng)
            commit = bivar.commitment()
            rows = []
            for i, nid in enumerate(self.node_ids):
                row = bivar.row(i + 1)
                rows.append(self.pub_keys[nid].encrypt(dumps(row), rng))
            master_g1 = G1_GEN * bivar.evaluate(0, 0)
            self.our_part = Part(commit, tuple(rows), master_g1)
            self._rng = rng

    def node_index(self, nid) -> Optional[int]:
        try:
            return self.node_ids.index(nid)
        except ValueError:
            return None

    # -- Part --------------------------------------------------------------

    def handle_part(self, sender_id, part: Part, rng=None):
        """Returns (Ack | None, FaultLog).  All participants must handle
        the identical Part sequence (including their own)."""
        faults = FaultLog()
        sender_idx = self.node_index(sender_id)
        if sender_idx is None:
            return None, faults
        if sender_idx in self.parts:
            return None, faults  # ignore duplicate parts (reference :315)
        if self.mock:
            return self._handle_part_mock(sender_id, sender_idx, part, faults)
        if not self._part_well_formed(part):
            faults.add(sender_id, FaultKind.INVALID_PART)
            return None, faults
        self.parts[sender_idx] = _ProposalState(part.commit, part.master_g1)
        if self.our_idx is None:
            return None, faults  # observer: no Ack
        commit_row = part.commit.row(self.our_idx + 1)
        ser_row = self.sec_key.decrypt(part.rows[self.our_idx])
        if ser_row is None:
            faults.add(sender_id, FaultKind.INVALID_PART)
            return None, faults
        try:
            row = loads(ser_row)
            assert isinstance(row, Poly) and row.degree == self.threshold
        except (SerializationError, AssertionError, Exception):
            faults.add(sender_id, FaultKind.INVALID_PART)
            return None, faults
        if row.commitment() != commit_row:
            faults.add(sender_id, FaultKind.INVALID_PART)
            return None, faults
        # row is valid: encrypt one evaluation for every node
        rng = rng if rng is not None else self._rng
        values = tuple(
            self.pub_keys[nid].encrypt(dumps(row.evaluate(j + 1)), rng)
            for j, nid in enumerate(self.node_ids)
        )
        return Ack(sender_idx, values), faults

    def _part_well_formed(self, part: Part) -> bool:
        if not isinstance(part, Part) or not isinstance(part.commit, BivarCommitment):
            return False
        if part.commit.degree != self.threshold or not part.commit.is_symmetric():
            return False
        if len(part.rows) != len(self.node_ids):
            return False
        if not isinstance(part.master_g1, G1):
            return False
        # consistency of the G1 master commitment with the G2 one:
        # e(A, P₂) == e(P₁, C(0,0))
        return pairing_check(
            [(part.master_g1, G2_GEN), (-G1_GEN, part.commit.evaluate(0, 0))]
        )

    def _handle_part_mock(self, sender_id, sender_idx, part, faults):
        seed = part.rows[self.our_idx if self.our_idx is not None else 0]
        if sha256(b"DKGSEED" + seed) != part.commit:
            faults.add(sender_id, FaultKind.INVALID_PART)
            return None, faults
        st = _ProposalState(part.commit, None)
        st.mock_seed = seed
        self.parts[sender_idx] = st
        if self.our_idx is None:
            return None, faults
        return Ack(sender_idx, (seed,) * len(self.node_ids)), faults

    # -- Ack ---------------------------------------------------------------

    def handle_ack(self, sender_id, ack: Ack) -> FaultLog:
        faults = FaultLog()
        sender_idx = self.node_index(sender_id)
        if sender_idx is None:
            return faults
        err = self._handle_ack_or_err(sender_idx, ack)
        if err is not None:
            faults.add(sender_id, FaultKind.INVALID_ACK)
        return faults

    def _handle_ack_or_err(self, sender_idx: int, ack: Ack) -> Optional[str]:
        if not isinstance(ack, Ack):
            return "malformed ack"
        if len(ack.values) != len(self.node_ids):
            return "wrong node count"
        if not isinstance(ack.proposer_idx, int) or isinstance(
            ack.proposer_idx, bool
        ):
            # the wire can carry anything here — an unhashable
            # proposer_idx would TypeError the dict lookup below
            return "malformed proposer index"
        part = self.parts.get(ack.proposer_idx)
        if part is None:
            return "sender does not exist"
        if sender_idx in part.acks:
            return "duplicate ack"
        part.acks.add(sender_idx)
        if self.our_idx is None:
            return None  # observer: nothing to decrypt
        if self.mock:
            if ack.values[self.our_idx] != part.mock_seed:
                part.acks.discard(sender_idx)
                return "wrong value"
            return None
        ser_val = self.sec_key.decrypt(ack.values[self.our_idx])
        if ser_val is None:
            part.acks.discard(sender_idx)
            return "value decryption failed"
        try:
            val = loads(ser_val)
            assert isinstance(val, int)
        except (SerializationError, AssertionError, Exception):
            part.acks.discard(sender_idx)
            return "deserialization failed"
        # the exact check of sync_key_gen.rs:449, in G2
        if part.commit.evaluate(self.our_idx + 1, sender_idx + 1) != G2_GEN * val:
            part.acks.discard(sender_idx)
            return "wrong value"
        part.values[sender_idx + 1] = val % F.R
        return None

    # -- readiness + generation -------------------------------------------

    def count_complete(self) -> int:
        return sum(
            1 for p in self.parts.values() if p.is_complete(self.threshold)
        )

    def is_node_ready(self, proposer_id) -> bool:
        idx = self.node_index(proposer_id)
        part = self.parts.get(idx) if idx is not None else None
        return part is not None and part.is_complete(self.threshold)

    def is_ready(self) -> bool:
        return self.count_complete() > self.threshold

    def generate(self):
        """Returns (public_key_set, secret_key_share | None).

        Only secure if ``is_ready()``; all participants must have handled
        the identical Part/Ack sequence."""
        complete = [
            (idx, p)
            for idx, p in sorted(self.parts.items())
            if p.is_complete(self.threshold)
        ]
        if self.mock:
            seed = sha256(
                b"DKGGROUP"
                + b"".join(
                    idx.to_bytes(4, "big") + p.mock_seed for idx, p in complete
                )
            )
            pk_set = M.MockPublicKeySet(seed, self.threshold)
            sks = (
                M.MockSecretKeyShare(seed, self.our_idx)
                if self.our_idx is not None
                else None
            )
            return pk_set, sks
        pk_commit = Commitment([])
        master_g1 = G1.infinity()
        sk_val: Optional[int] = 0 if self.our_idx is not None else None
        for idx, part in complete:
            pk_commit = pk_commit + part.commit.row(0)
            master_g1 = master_g1 + part.master_g1
            if sk_val is not None:
                pts = sorted(part.values.items())[: self.threshold + 1]
                if len(pts) <= self.threshold:
                    raise ValueError(
                        "not enough verified values to reconstruct the share"
                    )
                sk_val = (sk_val + interpolate_at_zero(pts)) % F.R
        pk_set = T.PublicKeySet(pk_commit, master_g1)
        sks = T.SecretKeyShare(sk_val) if sk_val is not None else None
        return pk_set, sks

    def into_network_info(self, ops=None):
        """Builds the post-DKG NetworkInfo (reference
        ``sync_key_gen.rs:416-420``)."""
        from ..core.network_info import NetworkInfo

        pk_set, sks = self.generate()
        return NetworkInfo(
            self.our_id, sks, self.sec_key, pk_set, self.pub_keys, ops=ops
        )
