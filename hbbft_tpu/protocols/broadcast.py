"""Reliable Broadcast (RBC) — erasure-coded, Merkle-authenticated.

Re-design of the reference ``src/broadcast.rs`` (707 LoC): the proposer
Reed-Solomon-encodes its value into N shards (N−2f data + 2f parity,
``broadcast.rs:310-312``), commits to them in a SHA-256 Merkle tree and
sends each node its shard + inclusion proof.  Three-phase Value → Echo →
Ready protocol with thresholds:

- Echo on first valid ``Value`` from the proposer (``:407-436``);
- Ready after N−f Echos with one root (``:460-466``);
- Ready-amplification at f+1 Readys (``:485-488``);
- decode + output at ≥ 2f+1 Readys ∧ ≥ N−2f Echos (``:521-551``),
  re-building the Merkle tree from reconstructed shards to detect an
  equivocating proposer (``:660-692``).

The RS encode and the two Merkle builds are the hot ops; they route
through ``netinfo.ops`` so the TPU backend can batch them across
broadcast instances (SURVEY §2.5 axis 1/5).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

from ..core.algorithm import DistAlgorithm, HbbftError
from ..core.fault import FaultKind
from ..core.network_info import NetworkInfo
from ..core.serialize import wire
from ..core.fault import log as _log
from ..core.step import Step, Target
from ..crypto.merkle import MerkleProof


@wire("BcValue")
@dataclasses.dataclass(frozen=True)
class BroadcastValue:
    proof: MerkleProof


@wire("BcEcho")
@dataclasses.dataclass(frozen=True)
class BroadcastEcho:
    proof: MerkleProof


@wire("BcReady")
@dataclasses.dataclass(frozen=True)
class BroadcastReady:
    root_hash: bytes


BroadcastMessage = Any  # one of the three dataclasses above


def frame_into_shards(
    value: bytes, data_shard_num: int, symbol: int = 1
) -> List[bytes]:
    """Length-prefix + pad + split into equal data shards (reference
    ``send_shards``, ``broadcast.rs:341-363``).  Shared by the protocol
    proposer path and the vectorized co-simulation round.  ``symbol``:
    the codec's symbol width — shard lengths round up to a multiple of
    it (2 for the GF(2^16) codec that lifts the 256-shard cap)."""
    payload = len(value).to_bytes(4, "big") + value
    shard_len = max(-(-len(payload) // data_shard_num), 1)
    shard_len = -(-shard_len // symbol) * symbol
    padded = payload.ljust(shard_len * data_shard_num, b"\x00")
    return [
        padded[i * shard_len : (i + 1) * shard_len]
        for i in range(data_shard_num)
    ]


def unframe_shards(shards: List[bytes], data_shard_num: int) -> Optional[bytes]:
    """Inverse of :func:`frame_into_shards`: join + strip the 4-byte
    length header (reference ``glue_shards``, ``broadcast.rs:697-707``).
    Returns None if the length header is inconsistent (a malformed
    proposal — the caller attributes the fault)."""
    payload = b"".join(shards[:data_shard_num])
    length = int.from_bytes(payload[:4], "big")
    if length > len(payload) - 4:
        return None
    return payload[4 : 4 + length]


class BroadcastError(HbbftError):
    pass


class InstanceCannotPropose(BroadcastError):
    pass


class Broadcast(DistAlgorithm):
    """One broadcast instance: ``proposer_id`` proposes, everyone delivers."""

    def __init__(self, netinfo: NetworkInfo, proposer_id):
        if not netinfo.is_node_validator(proposer_id):
            raise BroadcastError(f"unknown proposer {proposer_id!r}")
        self.netinfo = netinfo
        self.proposer_id = proposer_id
        self.parity_shard_num = 2 * netinfo.num_faulty
        self.data_shard_num = netinfo.num_nodes - self.parity_shard_num
        self.coding = netinfo.ops.rs_codec(
            self.data_shard_num, self.parity_shard_num
        )
        self.echo_sent = False
        self.ready_sent = False
        self.decided = False
        self.echos: Dict[Any, MerkleProof] = {}
        self.readys: Dict[Any, bytes] = {}

    # -- checkpointing -----------------------------------------------------
    # The codec is derived from the ops backend (it may wrap device
    # executables); snapshots carry only the shard counts and restore
    # rebuilds it from the re-injected backend (harness/checkpoint.py).

    def __getstate__(self):
        state = dict(self.__dict__)
        state.pop("coding", None)
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self.coding = self.netinfo.ops.rs_codec(
            self.data_shard_num, self.parity_shard_num
        )

    # -- DistAlgorithm -----------------------------------------------------

    def handle_input(self, value: bytes) -> Step:
        if self.netinfo.our_id != self.proposer_id:
            raise InstanceCannotPropose(
                "only the proposer may input a value"
            )
        proof, step = self._send_shards(bytes(value))
        step.extend(self._handle_value(self.netinfo.our_id, proof))
        return step

    def handle_message(self, sender_id, message) -> Step:
        if not self.netinfo.is_node_validator(sender_id):
            raise BroadcastError(f"unknown sender {sender_id!r}")
        if isinstance(message, BroadcastValue):
            return self._handle_value(sender_id, message.proof)
        if isinstance(message, BroadcastEcho):
            return self._handle_echo(sender_id, message.proof)
        if isinstance(message, BroadcastReady):
            return self._handle_ready(sender_id, message.root_hash)
        return Step.from_fault(sender_id, FaultKind.INVALID_MESSAGE)

    def terminated(self) -> bool:
        return self.decided

    def our_id(self):
        return self.netinfo.our_id

    # -- proposer path -----------------------------------------------------

    def _send_shards(self, value: bytes):
        """RS-encode + Merkle-commit the value; unicast proof i to node i
        (reference ``send_shards``, ``broadcast.rs:332-404``)."""
        data = frame_into_shards(
            value, self.data_shard_num, getattr(self.coding, "symbol", 1)
        )
        shards = self.coding.encode(data)
        mtree = self.netinfo.ops.merkle_tree(shards)
        step: Step = Step()
        our_proof: Optional[MerkleProof] = None
        for idx, nid in enumerate(self.netinfo.all_ids):
            proof = mtree.proof(idx)
            if nid == self.netinfo.our_id:
                our_proof = proof
            else:
                step.send_to(nid, BroadcastValue(proof))
        assert our_proof is not None
        return our_proof, step

    # -- handlers ----------------------------------------------------------

    def _handle_value(self, sender_id, proof: MerkleProof) -> Step:
        if sender_id != self.proposer_id:
            return Step.from_fault(
                sender_id, FaultKind.RECEIVED_VALUE_FROM_NON_PROPOSER
            )
        if self.echo_sent:
            # A second Value is ignored (reference keeps this non-fatal,
            # ``broadcast.rs:418-427``).
            return Step()
        if not self._validate_proof(proof, self.netinfo.our_id):
            return Step.from_fault(sender_id, FaultKind.INVALID_PROOF)
        return self._send_echo(proof)

    def _handle_echo(self, sender_id, proof: MerkleProof) -> Step:
        if sender_id in self.echos:
            return Step()
        if not self._validate_proof(proof, sender_id):
            return Step.from_fault(sender_id, FaultKind.INVALID_PROOF)
        root = proof.root_hash
        self.echos[sender_id] = proof
        if self.ready_sent or self._count_echos(root) < self.netinfo.num_correct:
            return self._compute_output(root)
        # N − f Echos with this root ⇒ multicast Ready
        return self._send_ready(root)

    def _handle_ready(self, sender_id, root: bytes) -> Step:
        if sender_id in self.readys:
            return Step()
        self.readys[sender_id] = root
        step: Step = Step()
        if (
            self._count_readys(root) == self.netinfo.num_faulty + 1
            and not self.ready_sent
        ):
            step.extend(self._send_ready(root))
        step.extend(self._compute_output(root))
        return step

    # -- sending (observers send nothing) ---------------------------------

    def _send_echo(self, proof: MerkleProof) -> Step:
        self.echo_sent = True
        if not self.netinfo.is_validator:
            return Step()
        step: Step = Step()
        step.send_all(BroadcastEcho(proof))
        step.extend(self._handle_echo(self.netinfo.our_id, proof))
        return step

    def _send_ready(self, root: bytes) -> Step:
        self.ready_sent = True
        if not self.netinfo.is_validator:
            return Step()
        step: Step = Step()
        step.send_all(BroadcastReady(root))
        step.extend(self._handle_ready(self.netinfo.our_id, root))
        return step

    # -- output ------------------------------------------------------------

    def _compute_output(self, root: bytes) -> Step:
        if (
            self.decided
            or self._count_readys(root) <= 2 * self.netinfo.num_faulty
            or self._count_echos(root) < self.data_shard_num
        ):
            return Step()
        # ≥ 2f+1 Readys and ≥ N−2f Echos: reconstruct all shards.
        slots: List[Optional[bytes]] = [None] * self.netinfo.num_nodes
        for proof in self.echos.values():
            if proof.root_hash == root:
                slots[proof.index] = proof.value
        try:
            shards = self.coding.reconstruct(slots)
        except ValueError:
            return Step()
        # Re-root the tree: detects a proposer that equivocated between
        # shard sets (reference ``decode_from_shards``,
        # ``broadcast.rs:660-692``).
        mtree = self.netinfo.ops.merkle_tree(shards)
        if mtree.root_hash != root:
            return Step.from_fault(
                self.proposer_id, FaultKind.BROADCAST_DECODING_FAILED
            )
        value = unframe_shards(shards, self.data_shard_num)
        if value is None:
            return Step.from_fault(
                self.proposer_id, FaultKind.BROADCAST_DECODING_FAILED
            )
        self.decided = True
        _log.debug(
            "%r: broadcast from %r delivered (%d bytes)",
            self.netinfo.our_id, self.proposer_id, len(value),
        )
        return Step.with_output(value)

    # -- helpers -----------------------------------------------------------

    def _validate_proof(self, proof: MerkleProof, nid) -> bool:
        """Proof must verify and carry the shard index assigned to ``nid``
        (reference ``validate_proof``, ``broadcast.rs:555-575``)."""
        if not isinstance(proof, MerkleProof):
            return False
        idx = self.netinfo.node_index(nid)
        return (
            idx is not None
            and proof.index == idx
            and isinstance(proof.value, bytes)
            and proof.validate(self.netinfo.num_nodes)
        )

    def _count_echos(self, root: bytes) -> int:
        return sum(1 for p in self.echos.values() if p.root_hash == root)

    def _count_readys(self, root: bytes) -> int:
        return sum(1 for r in self.readys.values() if r == root)


def random_message(rng, n_nodes: int = 4):
    """Generate a random (garbage) broadcast message for fuzz adversaries
    (reference ``rand::Rand`` impl, ``broadcast.rs:210-229``)."""
    kind = rng.randrange(3)
    if kind == 2:
        return BroadcastReady(rng.randrange(2**256).to_bytes(32, "big"))
    proof = MerkleProof(
        value=bytes(rng.randrange(256) for _ in range(8)),
        index=rng.randrange(n_nodes),
        lemma=tuple(
            rng.randrange(2**256).to_bytes(32, "big")
            for _ in range(max(1, n_nodes - 1).bit_length())
        ),
        root_hash=rng.randrange(2**256).to_bytes(32, "big"),
    )
    return BroadcastValue(proof) if kind == 0 else BroadcastEcho(proof)
