"""Common Coin — unique threshold signatures as shared randomness.

Reference: ``src/common_coin.rs`` (208 LoC).  On input, each validator
signs the round nonce with its threshold key share and multicasts the
share; incoming shares are verified against the sender's public key
share (bad shares are attributed as faults); once > f verified shares
are present *and* we provided input, the shares are Lagrange-combined,
the combined signature is verified against the master key, and its
parity bit is the coin value — identical at every correct node, and
unpredictable until f+1 nodes reveal shares.

Crypto cost per flip (network-wide): N share-signs, up to N² share
verifies, N combines — the first of the batched TPU kernel targets
(BASELINE config 2: 64 nodes × 1000 flips).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

from ..core.algorithm import CryptoError, DistAlgorithm, UnknownSenderError
from ..core.fault import FaultKind
from ..core.network_info import NetworkInfo
from ..core.serialize import wire
from ..core.step import Step


@wire("CoinMsg")
@dataclasses.dataclass(frozen=True)
class CommonCoinMessage:
    share: Any  # SignatureShare (real or mock)


class CommonCoin(DistAlgorithm):
    """One coin flip, named by a unique ``nonce``."""

    def __init__(self, netinfo: NetworkInfo, nonce: bytes):
        self.netinfo = netinfo
        self.nonce = bytes(nonce)
        self.received_shares: Dict[Any, Any] = {}
        self.had_input = False
        self._terminated = False

    # -- DistAlgorithm -----------------------------------------------------

    def handle_input(self, _input=None) -> Step:
        """Sends our threshold signature share if not yet sent."""
        if self.had_input:
            return Step()
        self.had_input = True
        return self._get_coin()

    def handle_message(self, sender_id, message) -> Step:
        if self._terminated:
            return Step()
        if not isinstance(message, CommonCoinMessage):
            return Step.from_fault(sender_id, FaultKind.INVALID_MESSAGE)
        return self._handle_share(sender_id, message.share)

    def terminated(self) -> bool:
        return self._terminated

    def our_id(self):
        return self.netinfo.our_id

    # -- internals ---------------------------------------------------------

    def _get_coin(self) -> Step:
        if not self.netinfo.is_validator:
            return self._try_output()
        share = self.netinfo.secret_key_share.sign(self.nonce)
        step: Step = Step()
        step.send_all(CommonCoinMessage(share))
        step.extend(self._handle_share(self.netinfo.our_id, share))
        return step

    def _handle_share(self, sender_id, share) -> Step:
        pk_share = self.netinfo.public_key_share(sender_id)
        if pk_share is None:
            raise UnknownSenderError(f"unknown sender {sender_id!r}")
        if sender_id in self.received_shares:
            return Step()
        try:
            ok = self.netinfo.ops.verify_sig_share(pk_share, share, self.nonce)
        except Exception:
            ok = False
        if not ok:
            return Step.from_fault(
                sender_id, FaultKind.INVALID_SIGNATURE_SHARE
            )
        self.received_shares[sender_id] = share
        return self._try_output()

    def _try_output(self) -> Step:
        if not self.had_input or len(self.received_shares) <= self.netinfo.num_faulty:
            return Step()
        sig = self._combine_and_verify_sig()
        self._terminated = True
        return Step.with_output(sig.parity())

    def _combine_and_verify_sig(self):
        shares_by_idx = {
            self.netinfo.node_index(nid): share
            for nid, share in self.received_shares.items()
        }
        pk_set = self.netinfo.public_key_set
        sig = pk_set.combine_signatures(shares_by_idx)
        if not pk_set.verify_signature(sig, self.nonce):
            # All contributing shares verified individually, so a failing
            # master signature indicates a local bug, not remote
            # Byzantine behaviour — abort loudly (reference
            # ``common_coin.rs:192-204``).
            raise CryptoError("combined coin signature failed verification")
        return sig


def make_nonce(
    invocation_id: bytes, session_id: int, proposer_index: int, epoch: int
) -> bytes:
    """Unique coin nonce binding the network invocation, HB session
    (epoch), proposer, and agreement epoch (reference
    ``agreement/mod.rs:154-166``)."""
    return (
        b"hbbft_tpu coin nonce|"
        + invocation_id
        + b"|%d|%d|%d" % (session_id, proposer_index, epoch)
    )
