"""Synchronized Binary Value Broadcast — the BVal/Aux phase of Agreement.

Reference: ``src/agreement/sbv_broadcast.rs`` (204 LoC).  Thresholds:
BVal relay at f+1, insert into ``bin_values`` at 2f+1 (first entry
triggers ``Aux``), output when ≥ N−f ``Aux`` messages carry values
inside ``bin_values``.  ``clear(init)`` re-seeds the next epoch's
instance from ``Term`` senders.
"""

from __future__ import annotations

import dataclasses
from typing import Any

from ..core.algorithm import DistAlgorithm
from ..core.fault import FaultKind
from ..core.network_info import NetworkInfo
from ..core.serialize import wire
from ..core.step import Step
from .bool_set import BoolMultimap, BoolSet


@wire("SbvBVal")
@dataclasses.dataclass(frozen=True)
class BVal:
    value: bool


@wire("SbvAux")
@dataclasses.dataclass(frozen=True)
class Aux:
    value: bool


class SbvBroadcast(DistAlgorithm):
    def __init__(self, netinfo: NetworkInfo):
        self.netinfo = netinfo
        self.bin_values = BoolSet.none()
        self.received_bval = BoolMultimap()
        self.sent_bval = BoolSet.none()
        self.received_aux = BoolMultimap()
        self._terminated = False

    # -- DistAlgorithm -----------------------------------------------------

    def handle_input(self, value: bool) -> Step:
        return self.send_bval(bool(value))

    def handle_message(self, sender_id, msg) -> Step:
        # a deserialized BVal/Aux can carry a non-bool value, which would
        # KeyError the {False, True} multimaps below
        if isinstance(msg, BVal):
            if not isinstance(msg.value, bool):
                return Step.from_fault(sender_id, FaultKind.INVALID_MESSAGE)
            return self.handle_bval(sender_id, msg.value)
        if isinstance(msg, Aux):
            if not isinstance(msg.value, bool):
                return Step.from_fault(sender_id, FaultKind.INVALID_MESSAGE)
            return self.handle_aux(sender_id, msg.value)
        return Step.from_fault(sender_id, FaultKind.INVALID_MESSAGE)

    def terminated(self) -> bool:
        return self._terminated

    def our_id(self):
        return self.netinfo.our_id

    # -- epoch reset -------------------------------------------------------

    def clear(self, init: BoolMultimap) -> None:
        """Reset for the next epoch; ``init`` values (from ``Term``
        senders) count as already-received BVal and Aux
        (reference ``sbv_broadcast.rs:102-108``)."""
        self.bin_values = BoolSet.none()
        self.received_bval = init.copy()
        self.sent_bval = BoolSet.none()
        self.received_aux = init.copy()
        self._terminated = False

    # -- handlers ----------------------------------------------------------

    def handle_bval(self, sender_id, b: bool) -> Step:
        if sender_id in self.received_bval[b]:
            return Step.from_fault(sender_id, FaultKind.DUPLICATE_BVAL)
        self.received_bval[b].add(sender_id)
        count = len(self.received_bval[b])
        step: Step = Step()
        if count == 2 * self.netinfo.num_faulty + 1:
            self.bin_values.insert(b)
            if len(self.bin_values) == 1:
                step.extend(self._send(Aux(b)))  # first entry: send Aux
            else:
                step.extend(self._try_output())
        if count == self.netinfo.num_faulty + 1:
            step.extend(self.send_bval(b))
        return step

    def handle_aux(self, sender_id, b: bool) -> Step:
        if sender_id in self.received_aux[b]:
            return Step.from_fault(sender_id, FaultKind.DUPLICATE_AUX)
        self.received_aux[b].add(sender_id)
        return self._try_output()

    # -- sending -----------------------------------------------------------

    def send_bval(self, b: bool) -> Step:
        if not self.sent_bval.insert(b):
            return Step()
        return self._send(BVal(b))

    def _send(self, msg) -> Step:
        if not self.netinfo.is_validator:
            return Step()
        step: Step = Step()
        step.send_all(msg)
        step.extend(self.handle_message(self.netinfo.our_id, msg))
        return step

    # -- output ------------------------------------------------------------

    def _try_output(self) -> Step:
        if self._terminated or self.bin_values == BoolSet.none():
            return Step()
        count, vals = self._count_aux()
        if count < self.netinfo.num_correct:
            return Step()
        self._terminated = True
        return Step.with_output(vals)

    def _count_aux(self):
        """Count Aux messages whose values lie inside ``bin_values``
        (reference ``count_aux``, ``sbv_broadcast.rs:193-203``)."""
        values = BoolSet.none()
        count = 0
        for b in self.bin_values:
            if self.received_aux[b]:
                values.insert(b)
                count += len(self.received_aux[b])
        return count, values
