"""hbbft_tpu.protocols subpackage."""
