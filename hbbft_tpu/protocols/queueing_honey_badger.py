"""QueueingHoneyBadger — DynamicHoneyBadger with a built-in tx queue.

Reference: ``src/queueing_honey_badger.rs`` (271 LoC).  On every input
and message, while ``can_propose`` (previous epoch done ∧ (queue
non-empty ∨ the anti-stall rule says we must)), proposes a random
sample of ``max(1, B/N)`` transactions from the first B queued
(``:255-268``); committed transactions are removed from the queue on
batch output.  Default batch size: 100 (``:118``).
"""

from __future__ import annotations

import random
from typing import Any, Iterable, Optional, Tuple

from ..core.algorithm import DistAlgorithm
from ..core.network_info import NetworkInfo
from ..core.step import Step
from .change import Change
from .dynamic_honey_badger import ChangeInput, DhbBatch, DynamicHoneyBadger, UserInput
from .transaction_queue import TransactionQueue


class QueueingHoneyBadger(DistAlgorithm):
    def __init__(
        self,
        dyn_hb: DynamicHoneyBadger,
        batch_size: int = 100,
        txs: Iterable = (),
        rng: Optional[random.Random] = None,
    ):
        self.dyn_hb = dyn_hb
        self.batch_size = batch_size
        self.queue = TransactionQueue(txs)
        # deterministic per-node default (badgerlint: determinism);
        # proposal sampling stays unpredictable to peers via the
        # secret-key-folded seed, and identical across re-runs
        self.rng = (
            rng
            if rng is not None
            else dyn_hb.netinfo.default_rng("queueing_honey_badger")
        )

    @classmethod
    def builder(cls, dyn_hb: DynamicHoneyBadger) -> "QueueingHoneyBadgerBuilder":
        return QueueingHoneyBadgerBuilder(dyn_hb)

    # -- DistAlgorithm -----------------------------------------------------

    def handle_input(self, input) -> Step:
        """A transaction to queue, or a `ChangeInput` vote."""
        if isinstance(input, ChangeInput):
            step = self.dyn_hb.handle_input(input)
        else:
            tx = input.contribution if isinstance(input, UserInput) else input
            self.queue.push(tx)
            step = Step()
        step.extend(self.propose())
        return step

    def handle_message(self, sender_id, message) -> Step:
        step = self.dyn_hb.handle_message(sender_id, message)
        for batch in step.output:
            self.queue.remove_all(batch.tx_iter())
        step.extend(self.propose())
        return step

    def terminated(self) -> bool:
        return False

    def our_id(self):
        return self.dyn_hb.our_id()

    # -- proposing ---------------------------------------------------------

    def can_propose(self) -> bool:
        if self.dyn_hb.has_input():
            return False  # previous epoch still in progress
        return len(self.queue) > 0 or self.dyn_hb.should_propose()

    def propose(self) -> Step:
        step: Step = Step()
        while self.can_propose():
            amount = max(
                1, self.batch_size // self.dyn_hb.netinfo.num_nodes
            )
            proposal = self.queue.choose(amount, self.batch_size, self.rng)
            inner = self.dyn_hb.handle_input(UserInput(proposal))
            for batch in inner.output:
                self.queue.remove_all(batch.tx_iter())
            step.extend(inner)
        return step


class QueueingHoneyBadgerBuilder:
    """Reference ``queueing_honey_badger.rs:97-157``."""

    def __init__(self, dyn_hb: DynamicHoneyBadger):
        self.dyn_hb = dyn_hb
        self._batch_size = 100
        self._rng: Optional[random.Random] = None

    def batch_size(self, value: int) -> "QueueingHoneyBadgerBuilder":
        self._batch_size = value
        return self

    def rng(self, rng: random.Random) -> "QueueingHoneyBadgerBuilder":
        self._rng = rng
        return self

    def build(self) -> Tuple[QueueingHoneyBadger, Step]:
        return self.build_with_transactions(())

    def build_with_transactions(
        self, txs: Iterable
    ) -> Tuple[QueueingHoneyBadger, Step]:
        qhb = QueueingHoneyBadger(
            self.dyn_hb, self._batch_size, txs, rng=self._rng
        )
        step = qhb.propose()
        return qhb, step
