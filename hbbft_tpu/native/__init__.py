"""ctypes loader for the C++ native host library (``native/``).

The reference's compute-heavy host work lives in native crates
(``ring`` SHA-256, ``merkle``, ``reed-solomon-erasure`` —
SURVEY.md §2.4); ours lives in ``native/hbbft_native.cpp`` built as
``libhbbft_native.so``.  This module loads it lazily (building it with
``make`` on first use if a compiler is present) and exposes typed
wrappers.  Every caller must tolerate :data:`lib` being ``None`` and
fall back to the pure-Python path — CI environments without a
toolchain still work, just slower.

Set ``HBBFT_TPU_NO_NATIVE=1`` to force the pure-Python path; the flag
is consulted on every :func:`available` call, so tests may toggle it
with ``monkeypatch.setenv`` to cross-check both implementations.
"""

from __future__ import annotations

import ctypes
import os
import pathlib
import subprocess
from typing import List, Optional, Sequence

import numpy as np

_NATIVE_DIR = pathlib.Path(__file__).resolve().parent.parent.parent / "native"
_SO_PATH = _NATIVE_DIR / "libhbbft_native.so"

lib: Optional[ctypes.CDLL] = None


def _try_load() -> Optional[ctypes.CDLL]:
    if os.environ.get("HBBFT_TPU_NO_NATIVE"):
        return None
    if (_NATIVE_DIR / "Makefile").exists():
        # Run make unconditionally (no-op when up to date) so edits to
        # the .cpp are never shadowed by a stale .so.  An fcntl lock
        # serialises concurrent builders (pytest-xdist workers); the
        # Makefile writes via a temp file + rename so a reader never
        # maps a half-written library.
        try:
            import fcntl

            with open(_NATIVE_DIR / ".build.lock", "w") as lock:
                fcntl.flock(lock, fcntl.LOCK_EX)
                subprocess.run(
                    ["make", "-C", str(_NATIVE_DIR)],
                    check=True,
                    capture_output=True,
                    timeout=120,
                )
        except Exception:
            pass
    if not _SO_PATH.exists():
        return None
    try:
        cdll = ctypes.CDLL(str(_SO_PATH))
    except OSError:
        return None
    try:
        return _bind(cdll)
    except AttributeError:
        # stale prebuilt library missing a newer symbol (and no working
        # toolchain to rebuild): degrade to the pure-Python path
        return None


def _bind(cdll):
    u8p = ctypes.POINTER(ctypes.c_uint8)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    cdll.hb_sha256_many.argtypes = [u8p, u64p, ctypes.c_uint64, u8p]
    cdll.hb_sha256_many.restype = None
    cdll.hb_merkle_total_hashes.argtypes = [ctypes.c_uint64]
    cdll.hb_merkle_total_hashes.restype = ctypes.c_uint64
    cdll.hb_merkle_build.argtypes = [u8p, u64p, ctypes.c_uint64, u8p]
    cdll.hb_merkle_build.restype = None
    cdll.hb_gf_matmul.argtypes = [
        u8p, u8p, u8p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
    ]
    cdll.hb_gf_matmul.restype = None
    cdll.hb_gf_mat_inv.argtypes = [u8p, u8p, ctypes.c_int]
    cdll.hb_gf_mat_inv.restype = ctypes.c_int
    u16p = ctypes.POINTER(ctypes.c_uint16)
    cdll.hb_gf16_matmul.argtypes = [
        u16p, u16p, u16p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
    ]
    cdll.hb_gf16_matmul.restype = None
    cdll.hb_gf16_mat_inv.argtypes = [u16p, u16p, ctypes.c_int]
    cdll.hb_gf16_mat_inv.restype = ctypes.c_int
    # BLS12-381 (native/bls12_381.cpp)
    b = ctypes.c_char_p
    cdll.hb_g1_mul.argtypes = [b, b, u8p]
    cdll.hb_g1_mul.restype = None
    cdll.hb_g2_mul.argtypes = [b, b, u8p]
    cdll.hb_g2_mul.restype = None
    cdll.hb_g1_msm.argtypes = [ctypes.c_uint64, b, b, u8p]
    cdll.hb_g1_msm.restype = None
    cdll.hb_g1_mul_many.argtypes = [ctypes.c_uint64, b, b, u8p]
    cdll.hb_g1_mul_many.restype = None
    cdll.hb_g2_msm.argtypes = [ctypes.c_uint64, b, b, u8p]
    cdll.hb_g2_msm.restype = None
    cdll.hb_g1_mul_outer.argtypes = [
        ctypes.c_uint64, ctypes.c_uint64, b, u8p, u8p,
    ]
    cdll.hb_g1_mul_outer.restype = None
    cdll.hb_g1_msm_many.argtypes = [
        ctypes.c_uint64, ctypes.c_uint64, u8p, u8p, u8p,
    ]
    cdll.hb_g1_msm_many.restype = None
    cdll.hb_g2_poly_eval_range.argtypes = [
        ctypes.c_uint64, b, ctypes.c_uint64, b, u8p,
    ]
    cdll.hb_g2_poly_eval_range.restype = None
    cdll.hb_g2_mul_many.argtypes = [ctypes.c_uint64, b, u8p, u8p]
    cdll.hb_g2_mul_many.restype = None
    cdll.hb_fr_matmul.argtypes = [
        ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64, u8p, u8p, u8p,
    ]
    cdll.hb_fr_matmul.restype = None
    cdll.hb_pairing_check.argtypes = [ctypes.c_uint64, b, b]
    cdll.hb_pairing_check.restype = ctypes.c_int
    cdll.hb_pairing.argtypes = [b, b, u8p]
    cdll.hb_pairing.restype = None
    cdll.hb_hash_to_g1.argtypes = [b, ctypes.c_uint64, b, ctypes.c_uint64, u8p]
    cdll.hb_hash_to_g1.restype = None
    return cdll


lib = _try_load()


def available() -> bool:
    return lib is not None and not os.environ.get("HBBFT_TPU_NO_NATIVE")


def backend():
    """This module when the native library is usable, else None — the
    single dispatch gate for all crypto fast paths."""
    import sys

    mod = sys.modules[__name__]
    return mod if available() else None


def _as_u8p(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _as_u64p(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))


def _concat_with_offsets(items: Sequence[bytes]):
    offsets = np.zeros(len(items) + 1, dtype=np.uint64)
    total = 0
    for i, it in enumerate(items):
        total += len(it)
        offsets[i + 1] = total
    data = np.frombuffer(b"".join(items), dtype=np.uint8) if total else np.zeros(1, dtype=np.uint8)
    return np.ascontiguousarray(data), offsets


def sha256_many(items: Sequence[bytes]) -> List[bytes]:
    """Batched SHA-256 (native).  Caller guarantees lib is loaded."""
    data, offsets = _concat_with_offsets(items)
    out = np.empty(32 * len(items), dtype=np.uint8)
    lib.hb_sha256_many(
        _as_u8p(data), _as_u64p(offsets), len(items), _as_u8p(out)
    )
    raw = out.tobytes()
    return [raw[32 * i : 32 * i + 32] for i in range(len(items))]


def merkle_levels(values: Sequence[bytes]) -> List[List[bytes]]:
    """Build every level of the Merkle tree natively; returns the same
    ``levels`` structure as :class:`hbbft_tpu.crypto.merkle.MerkleTree`
    (bottom level first, odd levels already duplicated)."""
    n = len(values)
    data, offsets = _concat_with_offsets(values)
    total = int(lib.hb_merkle_total_hashes(n))
    out = np.empty(32 * total, dtype=np.uint8)
    lib.hb_merkle_build(_as_u8p(data), _as_u64p(offsets), n, _as_u8p(out))
    raw = out.tobytes()
    levels: List[List[bytes]] = []
    pos = 0
    length = n
    while True:
        if length > 1 and (length & 1):
            length += 1
        levels.append(
            [raw[32 * (pos + i) : 32 * (pos + i + 1)] for i in range(length)]
        )
        pos += length
        if length <= 1:
            break
        length //= 2
    return levels


def gf_matmul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    a = np.ascontiguousarray(a, dtype=np.uint8)
    b = np.ascontiguousarray(b, dtype=np.uint8)
    m, k = a.shape
    k2, n = b.shape
    if k != k2:
        raise ValueError(f"shape mismatch: ({m},{k}) @ ({k2},{n})")
    out = np.empty((m, n), dtype=np.uint8)
    lib.hb_gf_matmul(_as_u8p(a), _as_u8p(b), _as_u8p(out), m, k, n)
    return out


def gf_mat_inv(m: np.ndarray) -> np.ndarray:
    m = np.ascontiguousarray(m, dtype=np.uint8)
    n = m.shape[0]
    out = np.empty((n, n), dtype=np.uint8)
    rc = lib.hb_gf_mat_inv(_as_u8p(m), _as_u8p(out), n)
    if rc != 0:
        raise ValueError("matrix not invertible over GF(256)")
    return out


def _as_u16p(a: np.ndarray):
    import ctypes

    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16))


def gf16_matmul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """(m,k)·(k,n) GF(2^16) product (AVX2 nibble-table row kernel)."""
    a = np.ascontiguousarray(a, dtype=np.uint16)
    b = np.ascontiguousarray(b, dtype=np.uint16)
    m, k = a.shape
    k2, n = b.shape
    if k != k2:
        raise ValueError(f"shape mismatch: ({m},{k}) @ ({k2},{n})")
    out = np.empty((m, n), dtype=np.uint16)
    lib.hb_gf16_matmul(_as_u16p(a), _as_u16p(b), _as_u16p(out), m, k, n)
    return out


def gf16_mat_inv(m: np.ndarray) -> np.ndarray:
    m = np.ascontiguousarray(m, dtype=np.uint16)
    n = m.shape[0]
    out = np.empty((n, n), dtype=np.uint16)
    rc = lib.hb_gf16_mat_inv(_as_u16p(m), _as_u16p(out), n)
    if rc != 0:
        raise ValueError("matrix not invertible over GF(2^16)")
    return out


# ---------------------------------------------------------------------------
# BLS12-381 wire helpers + wrappers (native/bls12_381.cpp)
#
# Raw affine big-endian wire format (not the compressed public format):
#   G1: 96 bytes x||y, all-zero = infinity
#   G2: 192 bytes x.c0||x.c1||y.c0||y.c1, all-zero = infinity
# Scalars: 32-byte big-endian (callers reduce mod r first).
# ---------------------------------------------------------------------------

_G1_INF = b"\x00" * 96
_G2_INF = b"\x00" * 192


def g1_wire(pt) -> bytes:
    w = getattr(pt, "_wire", None)
    if w is not None and len(w) == 96:  # length-tagged: a cached G2
        return w  # wire must not satisfy a (buggy) G1 call site
    a = pt.affine()
    if a is None:
        w = _G1_INF
    else:
        w = a[0].to_bytes(48, "big") + a[1].to_bytes(48, "big")
    try:
        pt._wire = w
    except AttributeError:  # assignment-restricted stand-ins (no slot)
        pass
    return w


def g1_unwire(raw: bytes, cls):
    if raw == _G1_INF:
        return cls.infinity()
    return cls(
        (
            int.from_bytes(raw[:48], "big"),
            int.from_bytes(raw[48:96], "big"),
            1,
        )
    )


def g2_wire(pt) -> bytes:
    w = getattr(pt, "_wire", None)
    if w is not None and len(w) == 192:  # see g1_wire length check
        return w
    a = pt.affine()
    if a is None:
        w = _G2_INF
    else:
        (x0, x1), (y0, y1) = a
        w = (
            x0.to_bytes(48, "big")
            + x1.to_bytes(48, "big")
            + y0.to_bytes(48, "big")
            + y1.to_bytes(48, "big")
        )
    try:
        pt._wire = w
    except AttributeError:
        pass
    return w


def g2_unwire(raw: bytes, cls):
    if raw == _G2_INF:
        return cls.infinity()
    v = [int.from_bytes(raw[i * 48 : (i + 1) * 48], "big") for i in range(4)]
    return cls(((v[0], v[1]), (v[2], v[3]), (1, 0)))


def g1_mul(pt_wire: bytes, k: int) -> bytes:
    out = np.empty(96, dtype=np.uint8)
    lib.hb_g1_mul(pt_wire, k.to_bytes(32, "big"), _as_u8p(out))
    return out.tobytes()


def g2_poly_eval_range(coeff_wires, n: int, order: int) -> list:
    """Evaluate a G2-coefficient polynomial at x = 1..n (wire outputs).

    Direct MSMs seed the first min(ncoeffs, n) points (scalar power
    rows computed here, mod the group ``order``); the rest follow by
    the forward-difference recurrence in native code — t additions per
    point, no scalar muls (the key-dealing shape: one commitment
    evaluated at every validator index)."""
    ncoeffs = len(coeff_wires)
    m = min(ncoeffs, n)
    rows = []
    for i in range(m):
        x = i + 1
        acc = 1
        for _ in range(ncoeffs):
            rows.append(acc.to_bytes(32, "big"))
            acc = acc * x % order
    out = np.empty(n * 192, dtype=np.uint8)
    lib.hb_g2_poly_eval_range(
        ncoeffs, b"".join(coeff_wires), n, b"".join(rows), _as_u8p(out)
    )
    raw = out.tobytes()
    return [raw[i * 192 : (i + 1) * 192] for i in range(n)]


def g1_mul_many(pt_wire: bytes, ks) -> list:
    """[k₀·P, k₁·P, …] for ONE shared base — one native call instead of
    a ctypes crossing + wire decode per product (the co-simulation's
    sign-one-nonce / decrypt-one-ciphertext shapes)."""
    n = len(ks)
    out = np.empty(n * 96, dtype=np.uint8)
    kbuf = b"".join(int(k).to_bytes(32, "big") for k in ks)
    lib.hb_g1_mul_many(n, pt_wire, kbuf, _as_u8p(out))
    raw = out.tobytes()
    return [raw[i * 96 : (i + 1) * 96] for i in range(n)]


def g1_mul_outer_raw(bases_wire: bytes, ks_be: np.ndarray) -> np.ndarray:
    """out[b][s] = ks[s]·base_b for every (base, scalar) pair — the
    whole epoch staging matrix in one native call (per-base fixed-base
    comb, shared scalar buffer).  ``bases_wire``: n_bases×96 B;
    ``ks_be``: uint8 array of n_scalars×32 big-endian scalars.
    Returns the raw n_bases×n_scalars×96 wire buffer, base-major."""
    ks_be = np.ascontiguousarray(ks_be, dtype=np.uint8).reshape(-1)
    n_scalars = len(ks_be) // 32
    n_bases = len(bases_wire) // 96
    out = np.empty(n_bases * n_scalars * 96, dtype=np.uint8)
    lib.hb_g1_mul_outer(
        n_bases, n_scalars, bases_wire, _as_u8p(ks_be), _as_u8p(out)
    )
    return out


def g1_msm_many_raw(
    n_msms: int, n_pts: int, pts_buf: np.ndarray, ks_be: np.ndarray
) -> np.ndarray:
    """Many MSMs over ONE shared scalar vector (the Lagrange-combine
    shape) — wires in, wires out, one ctypes crossing.  ``pts_buf``:
    uint8 n_msms×n_pts×96 row-major; ``ks_be``: n_pts×32 big-endian.
    Returns the raw n_msms×96 result buffer."""
    pts_buf = np.ascontiguousarray(pts_buf, dtype=np.uint8).reshape(-1)
    ks_be = np.ascontiguousarray(ks_be, dtype=np.uint8).reshape(-1)
    if len(pts_buf) != n_msms * n_pts * 96 or len(ks_be) != n_pts * 32:
        raise ValueError("g1_msm_many buffer shape mismatch")
    out = np.empty(n_msms * 96, dtype=np.uint8)
    lib.hb_g1_msm_many(
        n_msms, n_pts, _as_u8p(pts_buf), _as_u8p(ks_be), _as_u8p(out)
    )
    return out


def g2_mul(pt_wire: bytes, k: int) -> bytes:
    out = np.empty(192, dtype=np.uint8)
    lib.hb_g2_mul(pt_wire, k.to_bytes(32, "big"), _as_u8p(out))
    return out.tobytes()


def g2_mul_many_raw(pt_wire: bytes, ks_be: np.ndarray) -> np.ndarray:
    """[k₀·P, k₁·P, …] for ONE shared G2 base via the fixed-base comb.
    ``ks_be``: uint8 array of n×32 big-endian scalars; returns the raw
    n×192 wire buffer (the DKG dealing path keeps everything as
    buffers — no per-point Python objects)."""
    ks_be = np.ascontiguousarray(ks_be, dtype=np.uint8).reshape(-1)
    n = len(ks_be) // 32
    out = np.empty(n * 192, dtype=np.uint8)
    lib.hb_g2_mul_many(n, pt_wire, _as_u8p(ks_be), _as_u8p(out))
    return out


def fr_matmul(a: np.ndarray, b_: np.ndarray, n: int, k: int, m: int) -> np.ndarray:
    """[n×k]·[k×m] over the scalar field Fr — entries are 32-byte
    big-endian scalars in flat uint8 buffers (the DKG's bivariate
    row/value-grid algebra at co-simulation scale)."""
    a = np.ascontiguousarray(a, dtype=np.uint8).reshape(-1)
    b_ = np.ascontiguousarray(b_, dtype=np.uint8).reshape(-1)
    if len(a) != n * k * 32 or len(b_) != k * m * 32:
        raise ValueError("fr_matmul buffer shape mismatch")
    out = np.empty(n * m * 32, dtype=np.uint8)
    lib.hb_fr_matmul(n, k, m, _as_u8p(a), _as_u8p(b_), _as_u8p(out))
    return out


def g1_msm(pts_wire: Sequence[bytes], scalars: Sequence[int]) -> bytes:
    if len(pts_wire) != len(scalars):
        raise ValueError(
            f"msm length mismatch: {len(pts_wire)} points, {len(scalars)} scalars"
        )
    out = np.empty(96, dtype=np.uint8)
    lib.hb_g1_msm(
        len(pts_wire),
        b"".join(pts_wire),
        b"".join(k.to_bytes(32, "big") for k in scalars),
        _as_u8p(out),
    )
    return out.tobytes()


def g2_msm(pts_wire: Sequence[bytes], scalars: Sequence[int]) -> bytes:
    if len(pts_wire) != len(scalars):
        raise ValueError(
            f"msm length mismatch: {len(pts_wire)} points, {len(scalars)} scalars"
        )
    out = np.empty(192, dtype=np.uint8)
    lib.hb_g2_msm(
        len(pts_wire),
        b"".join(pts_wire),
        b"".join(k.to_bytes(32, "big") for k in scalars),
        _as_u8p(out),
    )
    return out.tobytes()


def pairing_check(g1s_wire: Sequence[bytes], g2s_wire: Sequence[bytes]) -> bool:
    return bool(
        lib.hb_pairing_check(len(g1s_wire), b"".join(g1s_wire), b"".join(g2s_wire))
    )


def pairing_bytes(g1_wire_: bytes, g2_wire_: bytes) -> bytes:
    """e(P,Q)³ as 576 canonical bytes (12 Fq coeffs, Python tuple order)."""
    out = np.empty(576, dtype=np.uint8)
    lib.hb_pairing(g1_wire_, g2_wire_, _as_u8p(out))
    return out.tobytes()


def hash_to_g1_bytes(msg: bytes, dst: bytes) -> bytes:
    if len(dst) > 255:
        # the oracle encodes len(dst) as one byte and raises past 255
        raise OverflowError("domain separation tag longer than 255 bytes")
    out = np.empty(96, dtype=np.uint8)
    lib.hb_hash_to_g1(msg, len(msg), dst, len(dst), _as_u8p(out))
    return out.tobytes()
