"""ctypes loader for the C++ native host library (``native/``).

The reference's compute-heavy host work lives in native crates
(``ring`` SHA-256, ``merkle``, ``reed-solomon-erasure`` —
SURVEY.md §2.4); ours lives in ``native/hbbft_native.cpp`` built as
``libhbbft_native.so``.  This module loads it lazily (building it with
``make`` on first use if a compiler is present) and exposes typed
wrappers.  Every caller must tolerate :data:`lib` being ``None`` and
fall back to the pure-Python path — CI environments without a
toolchain still work, just slower.

Set ``HBBFT_TPU_NO_NATIVE=1`` to force the pure-Python path; the flag
is consulted on every :func:`available` call, so tests may toggle it
with ``monkeypatch.setenv`` to cross-check both implementations.
"""

from __future__ import annotations

import ctypes
import os
import pathlib
import subprocess
from typing import List, Optional, Sequence

import numpy as np

_NATIVE_DIR = pathlib.Path(__file__).resolve().parent.parent.parent / "native"
_SO_PATH = _NATIVE_DIR / "libhbbft_native.so"

lib: Optional[ctypes.CDLL] = None


def _try_load() -> Optional[ctypes.CDLL]:
    if os.environ.get("HBBFT_TPU_NO_NATIVE"):
        return None
    if (_NATIVE_DIR / "Makefile").exists():
        # Run make unconditionally (no-op when up to date) so edits to
        # the .cpp are never shadowed by a stale .so.  An fcntl lock
        # serialises concurrent builders (pytest-xdist workers); the
        # Makefile writes via a temp file + rename so a reader never
        # maps a half-written library.
        try:
            import fcntl

            with open(_NATIVE_DIR / ".build.lock", "w") as lock:
                fcntl.flock(lock, fcntl.LOCK_EX)
                subprocess.run(
                    ["make", "-C", str(_NATIVE_DIR)],
                    check=True,
                    capture_output=True,
                    timeout=120,
                )
        except Exception:
            pass
    if not _SO_PATH.exists():
        return None
    try:
        cdll = ctypes.CDLL(str(_SO_PATH))
    except OSError:
        return None
    u8p = ctypes.POINTER(ctypes.c_uint8)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    cdll.hb_sha256_many.argtypes = [u8p, u64p, ctypes.c_uint64, u8p]
    cdll.hb_sha256_many.restype = None
    cdll.hb_merkle_total_hashes.argtypes = [ctypes.c_uint64]
    cdll.hb_merkle_total_hashes.restype = ctypes.c_uint64
    cdll.hb_merkle_build.argtypes = [u8p, u64p, ctypes.c_uint64, u8p]
    cdll.hb_merkle_build.restype = None
    cdll.hb_gf_matmul.argtypes = [
        u8p, u8p, u8p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
    ]
    cdll.hb_gf_matmul.restype = None
    cdll.hb_gf_mat_inv.argtypes = [u8p, u8p, ctypes.c_int]
    cdll.hb_gf_mat_inv.restype = ctypes.c_int
    return cdll


lib = _try_load()


def available() -> bool:
    return lib is not None and not os.environ.get("HBBFT_TPU_NO_NATIVE")


def _as_u8p(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _as_u64p(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))


def _concat_with_offsets(items: Sequence[bytes]):
    offsets = np.zeros(len(items) + 1, dtype=np.uint64)
    total = 0
    for i, it in enumerate(items):
        total += len(it)
        offsets[i + 1] = total
    data = np.frombuffer(b"".join(items), dtype=np.uint8) if total else np.zeros(1, dtype=np.uint8)
    return np.ascontiguousarray(data), offsets


def sha256_many(items: Sequence[bytes]) -> List[bytes]:
    """Batched SHA-256 (native).  Caller guarantees lib is loaded."""
    data, offsets = _concat_with_offsets(items)
    out = np.empty(32 * len(items), dtype=np.uint8)
    lib.hb_sha256_many(
        _as_u8p(data), _as_u64p(offsets), len(items), _as_u8p(out)
    )
    raw = out.tobytes()
    return [raw[32 * i : 32 * i + 32] for i in range(len(items))]


def merkle_levels(values: Sequence[bytes]) -> List[List[bytes]]:
    """Build every level of the Merkle tree natively; returns the same
    ``levels`` structure as :class:`hbbft_tpu.crypto.merkle.MerkleTree`
    (bottom level first, odd levels already duplicated)."""
    n = len(values)
    data, offsets = _concat_with_offsets(values)
    total = int(lib.hb_merkle_total_hashes(n))
    out = np.empty(32 * total, dtype=np.uint8)
    lib.hb_merkle_build(_as_u8p(data), _as_u64p(offsets), n, _as_u8p(out))
    raw = out.tobytes()
    levels: List[List[bytes]] = []
    pos = 0
    length = n
    while True:
        if length > 1 and (length & 1):
            length += 1
        levels.append(
            [raw[32 * (pos + i) : 32 * (pos + i + 1)] for i in range(length)]
        )
        pos += length
        if length <= 1:
            break
        length //= 2
    return levels


def gf_matmul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    a = np.ascontiguousarray(a, dtype=np.uint8)
    b = np.ascontiguousarray(b, dtype=np.uint8)
    m, k = a.shape
    k2, n = b.shape
    if k != k2:
        raise ValueError(f"shape mismatch: ({m},{k}) @ ({k2},{n})")
    out = np.empty((m, n), dtype=np.uint8)
    lib.hb_gf_matmul(_as_u8p(a), _as_u8p(b), _as_u8p(out), m, k, n)
    return out


def gf_mat_inv(m: np.ndarray) -> np.ndarray:
    m = np.ascontiguousarray(m, dtype=np.uint8)
    n = m.shape[0]
    out = np.empty((n, n), dtype=np.uint8)
    rc = lib.hb_gf_mat_inv(_as_u8p(m), _as_u8p(out), n)
    if rc != 0:
        raise ValueError("matrix not invertible over GF(256)")
    return out
