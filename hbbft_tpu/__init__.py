"""hbbft_tpu — a TPU-native Honey Badger BFT consensus framework.

A from-scratch re-design of the capabilities of ``poanetwork/hbbft``
(the Rust Honey Badger Byzantine Fault Tolerant consensus library) for
TPU hardware: deterministic sans-IO protocol state machines on the host,
with the per-epoch threshold cryptography (BLS12-381 share operations,
Reed-Solomon erasure coding, SHA-256 Merkle hashing) executing as
batched JAX kernels behind a ``CryptoBackend`` seam.

Layer map (mirrors SURVEY.md §1):
- ``core``      — Step/Target/DistAlgorithm/FaultLog/NetworkInfo (L1)
- ``crypto``    — BLS12-381, threshold schemes, RS, Merkle (L0, CPU path)
- ``ops``       — batched JAX/TPU kernels for the L0 hot ops
- ``parallel``  — device-mesh sharding of the batched kernels
- ``protocols`` — Broadcast, CommonCoin, Agreement, CommonSubset,
                  HoneyBadger, SyncKeyGen, DynamicHoneyBadger,
                  QueueingHoneyBadger (L2–L4)
- ``harness``   — adversarial test network + virtual-time simulator (L5)
"""

__version__ = "0.1.0"

from .core.algorithm import DistAlgorithm, HbbftError
from .core.fault import Fault, FaultKind, FaultLog
from .core.network_info import NetworkInfo
from .core.step import SourcedMessage, Step, Target, TargetedMessage

__all__ = [
    "DistAlgorithm",
    "HbbftError",
    "Fault",
    "FaultKind",
    "FaultLog",
    "NetworkInfo",
    "SourcedMessage",
    "Step",
    "Target",
    "TargetedMessage",
]
