"""Real-network transports for the sans-IO protocol stack."""

from .tcp import TcpNode, generate_keys_for  # noqa: F401
