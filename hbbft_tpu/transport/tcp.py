"""asyncio TCP transport — running the protocols over real sockets.

Re-design of the reference's example transport
(``examples/network/{connection,commst,messaging,node}.rs``, 528 LoC of
thread-per-connection Rust): same capabilities, idiomatic asyncio.

Design kept from the reference:

- **Node identity = socket address**, and the validator set is the
  *sorted* address list, so every node derives the identical set without
  coordination (``connection.rs:20-47``).
- **Deterministic connect/accept split**: for each peer pair, the
  lexicographically *smaller* address dials and the larger accepts —
  exactly one connection per pair, no tie-breaking races.
- **Routing hub**: the algorithm's ``Step.messages`` are routed by
  ``Target.{all,to}`` onto per-peer links (``messaging.rs:89-148``).

Deviations (deliberate):

- Frames are length-prefixed (4-byte big-endian) canonical-codec bytes
  (``core/serialize.py``) — the reference streams length-free bincode,
  which cannot resynchronize after a bad frame.
- One event loop replaces the reference's thread-per-connection +
  crossbeam channel mesh; the algorithm remains single-threaded by
  construction, matching the library's sans-IO contract.

The reference example runs a single ``Broadcast`` with placeholder keys
(``node.rs:105-118``); :func:`generate_keys_for` reproduces that spirit:
each node independently deals the *same* deterministic (INSECURE) key
set from the sorted address list.  Production deployments bootstrap real
keys via the dealerless DKG (``protocols/sync_key_gen.py``).
"""

from __future__ import annotations

import asyncio
import random
from typing import Any, Callable, Dict, List, Optional

from ..core.fault import Fault, FaultKind
from ..core.network_info import NetworkInfo
from ..core.serialize import SerializationError, dumps, loads
from ..core.step import Step
from ..obs import recorder as _obs

_LEN_BYTES = 4
_MAX_FRAME = 64 * 1024 * 1024

# Racecheck hook (analysis/racecheck.py): when the runtime lockset
# checker is installed it replaces this with a callable that wraps each
# new node's per-connection containers (_writers/outputs/faults) in
# tracked views, so concurrent connection handling is race-checked.
_TRACK_NODE: Optional[Callable[["TcpNode"], None]] = None


def generate_keys_for(addresses: List[str], our_addr: str) -> NetworkInfo:
    """Placeholder key dealing (INSECURE — demo/test only, like the
    reference's placeholder keys): every node derives the identical
    mock key set deterministically from the sorted address list."""
    ids = sorted(addresses)
    rng = random.Random("hbbft_tpu-tcp|" + "|".join(ids))
    netinfos = NetworkInfo.generate_map(ids, rng, mock=True)
    return netinfos[our_addr]


async def _read_frame(reader: asyncio.StreamReader) -> Any:
    message, _ = await _read_frame_sized(reader)
    return message


async def _read_frame_sized(reader: asyncio.StreamReader) -> Any:
    """→ (message, frame length in payload bytes)."""
    header = await reader.readexactly(_LEN_BYTES)
    length = int.from_bytes(header, "big")
    if length > _MAX_FRAME:
        raise ConnectionError(f"oversized frame: {length} bytes")
    return loads(await reader.readexactly(length)), length


def _frame(message: Any) -> bytes:
    payload = dumps(message)
    return len(payload).to_bytes(_LEN_BYTES, "big") + payload


class TcpNode:
    """One consensus node: an algorithm instance wired to its peers over
    TCP (reference ``Node::run``, ``node.rs:60-137``).

    **Security note (demo transport only)**: peer identity in the
    handshake is self-reported and unauthenticated — any socket that
    can reach the listener may claim any address in ``peer_addrs``
    (exactly like the reference example's plain-TCP handshake,
    ``connection.rs:20-47``).  A handshake for an address that is
    already connected is rejected (no impostor can displace a live
    link), but production use requires an authenticated transport
    (TLS, or a signature over the handshake with the peer's known
    public key)."""

    def __init__(
        self,
        our_addr: str,
        peer_addrs: List[str],
        new_algo: Callable[[NetworkInfo], Any],
        netinfo: Optional[NetworkInfo] = None,
        dial_retries: int = 50,
    ):
        self.our_addr = our_addr
        self.dial_retries = dial_retries
        self.peer_addrs = sorted(set(peer_addrs) - {our_addr})
        self.all_addrs = sorted(self.peer_addrs + [our_addr])
        self.netinfo = netinfo or generate_keys_for(self.all_addrs, our_addr)
        self.algo = new_algo(self.netinfo)
        self.outputs: List[Any] = []
        self.faults: List[Any] = []
        # Optional synchronous observer invoked once per algorithm
        # output (e.g. the serving gateway's commit-ack watcher); a
        # misbehaving hook must not take down the protocol pump.
        self.on_output: Optional[Callable[[Any], None]] = None
        self._writers: Dict[str, asyncio.StreamWriter] = {}
        self._inbox: asyncio.Queue = asyncio.Queue()
        self._server: Optional[asyncio.base_events.Server] = None
        self._tasks: List[asyncio.Task] = []
        self._connected = asyncio.Event()
        if _TRACK_NODE is not None:
            _TRACK_NODE(self)

    # -- connection management --------------------------------------------

    async def start(self, mesh_timeout: Optional[float] = None) -> None:
        """Bind our listener, dial every larger-address peer (the
        smaller address always dials — one connection per pair), and
        block until the full mesh is up.

        ``mesh_timeout``: overall deadline in seconds for the mesh to
        complete; ``ConnectionError`` on expiry instead of waiting
        forever (a dialed peer that registered and then dropped is
        tolerated like any silent node — only *failed dials* and the
        deadline abort startup)."""
        deadline = (
            None
            if mesh_timeout is None
            else asyncio.get_event_loop().time() + mesh_timeout
        )
        host, port = self.our_addr.rsplit(":", 1)
        self._server = await asyncio.start_server(
            self._on_accept, host, int(port)
        )
        # we dial every peer with a larger address; they dial us
        for peer in self.peer_addrs:
            if self.our_addr < peer:
                self._tasks.append(
                    asyncio.ensure_future(self._dial(peer))
                )
        # wait for the mesh, surfacing dial failures instead of hanging
        waiter = asyncio.ensure_future(self._connected.wait())
        pending = set(self._tasks)
        try:
            while not self._connected.is_set():
                wait_for = None
                if deadline is not None:
                    wait_for = deadline - asyncio.get_event_loop().time()
                    if wait_for <= 0:
                        raise ConnectionError(
                            f"mesh incomplete after {mesh_timeout}s "
                            f"({len(self._writers)}/{len(self.peer_addrs)} "
                            "links up)"
                        )
                done, _ = await asyncio.wait(
                    {waiter} | pending,
                    return_when=asyncio.FIRST_COMPLETED,
                    timeout=wait_for,
                )
                for t in done:
                    if t is waiter:
                        continue
                    pending.discard(t)
                    exc = t.exception()
                    if exc is not None:
                        raise exc
        finally:
            if not waiter.done():
                waiter.cancel()

    async def _dial(self, peer: str) -> None:
        host, port = peer.rsplit(":", 1)
        for attempt in range(self.dial_retries):
            try:
                reader, writer = await asyncio.open_connection(host, int(port))
                break
            except OSError:
                await asyncio.sleep(0.05 * (attempt + 1))
        else:
            raise ConnectionError(f"could not reach peer {peer}")
        # handshake: announce our address so the acceptor learns who we are
        writer.write(_frame(self.our_addr))
        await writer.drain()
        self._register(peer, writer)
        try:
            await self._recv_loop(peer, reader)
        finally:
            self._unregister(peer, writer)

    async def _on_accept(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        try:
            peer = await _read_frame(reader)
        except (
            asyncio.IncompleteReadError,
            ConnectionError,
            SerializationError,
        ):
            writer.close()
            return
        if (
            not isinstance(peer, (str, int))
            or peer not in self.peer_addrs
            or peer in self._writers
        ):
            # a non-id handshake payload (the wire can carry anything,
            # including an unhashable value that would TypeError the
            # membership tests), an unknown claim, or an impostor
            # claiming a peer whose link
            # is already LIVE — reject rather than displace the writer.
            # (Dead links are unregistered on recv-loop exit, so a
            # legitimately restarted peer can always re-handshake; a
            # peer reconnecting FASTER than its stale link's EOF is
            # observed gets refused once and must retry — acceptable
            # for this demo transport, a production one would probe
            # the existing writer on a conflicting handshake.)
            writer.close()
            return
        self._register(peer, writer)
        try:
            await self._recv_loop(peer, reader)
        finally:
            self._unregister(peer, writer)

    def _register(self, peer: str, writer: asyncio.StreamWriter) -> None:
        self._writers[peer] = writer
        if len(self._writers) == len(self.peer_addrs):
            self._connected.set()

    def _unregister(self, peer: str, writer: asyncio.StreamWriter) -> None:
        """Drop a dead link so the peer can reconnect (only if it is
        still the registered writer — a newer link is left alone)."""
        if self._writers.get(peer) is writer:
            del self._writers[peer]

    async def _recv_loop(self, peer: str, reader: asyncio.StreamReader) -> None:
        while True:
            try:
                message, size = await _read_frame_sized(reader)
            except (asyncio.IncompleteReadError, ConnectionError, OSError):
                return  # peer closed; the protocol tolerates f silent nodes
            except SerializationError:
                continue  # malformed frame: drop it, the length-prefixed
                # stream stays aligned on the next frame
            rec = _obs.ACTIVE
            if rec is not None:
                rec.event("wire_recv", peer=peer, size=size)
                rec.count("wire.recv_frames")
                rec.count("wire.recv_bytes", size)
            await self._inbox.put((peer, message))

    # -- the protocol pump --------------------------------------------------

    async def _route(self, step: Step) -> None:
        for out in step.output:
            self.outputs.append(out)
            if self.on_output is not None:
                try:
                    self.on_output(out)
                except Exception:
                    rec = _obs.ACTIVE
                    if rec is not None:
                        rec.count("wire.output_hook_errors")
        self.faults.extend(step.fault_log)
        rec = _obs.ACTIVE
        touched = []
        for tm in step.messages:
            if tm.target.is_all:
                targets = self.peer_addrs
            else:
                targets = [tm.target.node] if tm.target.node != self.our_addr else []
            frame = _frame(tm.message)
            kind = "all" if tm.target.is_all else "node"
            for peer in targets:
                w = self._writers.get(peer)
                if w is not None:
                    w.write(frame)
                    touched.append(w)
                    if rec is not None:
                        rec.event(
                            "wire_send",
                            peer=peer,
                            size=len(frame) - _LEN_BYTES,
                            kind=kind,
                        )
                        rec.count("wire.sent_frames")
                        rec.count("wire.sent_bytes", len(frame) - _LEN_BYTES)
        for w in touched:
            try:
                await w.drain()
            except (ConnectionError, OSError):
                pass

    async def input(self, value: Any) -> None:
        await self._route(self.algo.handle_input(value))

    async def run(
        self,
        until: Optional[Callable[["TcpNode"], bool]] = None,
        timeout: Optional[float] = None,
    ) -> List[Any]:
        """Pump messages until ``until(self)`` (default: the algorithm
        terminates).  Returns the collected outputs."""
        done = until or (lambda node: node.algo.terminated())
        loop = asyncio.get_event_loop()
        deadline = loop.time() + timeout if timeout is not None else None
        while not done(self):
            get = self._inbox.get()
            if deadline is not None:
                remaining = deadline - loop.time()
                if remaining <= 0:
                    raise asyncio.TimeoutError("consensus did not finish")
                sender, message = await asyncio.wait_for(get, remaining)
            else:
                sender, message = await get
            try:
                step = self.algo.handle_message(sender, message)
            except Exception:
                # A deserializable-but-malformed message slipped past the
                # handler's own guards.  Never crash the pump on remote
                # input — but never drop it silently either: attribute
                # it so the failure is visible in faults + obs counters.
                self.faults.append(Fault(sender, FaultKind.INVALID_MESSAGE))
                rec = _obs.ACTIVE
                if rec is not None:
                    rec.count("wire.handler_errors")
                continue
            await self._route(step)
        return self.outputs

    async def close(self) -> None:
        for t in self._tasks:
            t.cancel()
        for w in self._writers.values():
            w.close()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
