"""asyncio TCP transport — running the protocols over real sockets.

Re-design of the reference's example transport
(``examples/network/{connection,commst,messaging,node}.rs``, 528 LoC of
thread-per-connection Rust): same capabilities, idiomatic asyncio.

Design kept from the reference:

- **Node identity = socket address**, and the validator set is the
  *sorted* address list, so every node derives the identical set without
  coordination (``connection.rs:20-47``).
- **Deterministic connect/accept split**: for each peer pair, the
  lexicographically *smaller* address dials and the larger accepts —
  exactly one connection per pair, no tie-breaking races.
- **Routing hub**: the algorithm's ``Step.messages`` are routed by
  ``Target.{all,to}`` onto per-peer links (``messaging.rs:89-148``).

Deviations (deliberate):

- Frames are length-prefixed (4-byte big-endian) canonical-codec bytes
  (``core/serialize.py``) — the reference streams length-free bincode,
  which cannot resynchronize after a bad frame.
- One event loop replaces the reference's thread-per-connection +
  crossbeam channel mesh; the algorithm remains single-threaded by
  construction, matching the library's sans-IO contract.

**Session resumption** (crash-recovery PR): every data frame is wrapped
in ``SeqData`` carrying a per-link monotonic sequence number; each link
opens with a ``ResumeHello``/``ResumeWelcome`` handshake exchanging the
highest sequence number either side has *consumed*.  The sender keeps a
bounded outbound replay buffer — frames a peer never acknowledged, plus
everything routed while the peer was down — and on (re)connect replays
exactly the frames above the peer's reported high-water mark; the
receiver drops duplicates by sequence number.  Combined with the
write-ahead log (``recover/``), a validator SIGKILLed mid-epoch neither
loses nor double-applies a frame.  A dead link is redialed forever with
jittered exponential backoff (the dial side owns reconnection, keeping
the one-connection-per-pair invariant).

**State transfer** (dark-peer catch-up PR): a peer dark longer than the
replay-buffer bound cannot be caught up by replay — the evicted frames
are gone.  The receiver detects the hole as a sequence *gap* on the
first replayed frame and, when a ``recover.transfer.CatchupManager`` is
attached, escalates into a Byzantine-safe snapshot fetch over the
``St*`` control frames below (request → f+1 digest quorum → chunked
payload → verify → install) instead of severing the stream.  The
transport owns only the frame vocabulary and the gap/hold hooks; the
protocol lives in ``recover/transfer.py``.

The reference example runs a single ``Broadcast`` with placeholder keys
(``node.rs:105-118``); :func:`generate_keys_for` reproduces that spirit:
each node independently deals the *same* deterministic (INSECURE) key
set from the sorted address list.  Production deployments bootstrap real
keys via the dealerless DKG (``protocols/sync_key_gen.py``).
"""

from __future__ import annotations

import asyncio
import dataclasses
import random
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

from ..core.fault import Fault, FaultKind
from ..core.network_info import NetworkInfo
from ..core.serialize import SerializationError, dumps, loads, wire
from ..core.step import Step
from ..obs import recorder as _obs

_LEN_BYTES = 4
_MAX_FRAME = 64 * 1024 * 1024

# Session-resumption bounds.  Sequence numbers are attacker-controlled
# wire ints — every use is behind ``_seq_ok`` and they never size an
# allocation (the replay buffer is bounded by OUR frame/byte caps, the
# peer's number only selects a trim point).
_MAX_SEQ = 2**63
_REPLAY_MAX_FRAMES = 4096
_REPLAY_MAX_BYTES = 16 * 1024 * 1024
_ACK_EVERY = 64
_REDIAL_BASE_S = 0.05
_REDIAL_CAP_S = 2.0

# State-transfer bounds (the ``St*`` frames below; recover/transfer.py
# drives the protocol).  A snapshot payload is chunked so no single
# frame nears ``_MAX_FRAME``, and every size/offset/index field is an
# attacker-controlled wire int: the receiving side accepts a payload
# only up to ``_ST_MAX_BYTES``, accumulates received bytes instead of
# pre-allocating from a claimed size, and rejects any chunk whose
# offset/length stray from the strict in-order layout.
_ST_CHUNK_BYTES = 256 * 1024
_ST_MAX_BYTES = 32 * 1024 * 1024
_ST_MAX_CHUNKS = _ST_MAX_BYTES // _ST_CHUNK_BYTES

# Racecheck hook (analysis/racecheck.py): when the runtime lockset
# checker is installed it replaces this with a callable that wraps each
# new node's per-connection containers (_writers/outputs/faults and the
# replay-buffer map) in tracked views, so concurrent connection
# handling is race-checked.
_TRACK_NODE: Optional[Callable[["TcpNode"], None]] = None


@wire("RsHello")
@dataclasses.dataclass(frozen=True)
class ResumeHello:
    """Link-opening handshake (dial side): who we are + the highest
    sequence number we have consumed from this peer (0 = fresh)."""

    addr: Any
    recv_seq: Any


@wire("RsWelcome")
@dataclasses.dataclass(frozen=True)
class ResumeWelcome:
    """Accept side's reply: the highest sequence number *it* has
    consumed from us, so the dialer can trim + replay its buffer."""

    recv_seq: Any


@wire("RsData")
@dataclasses.dataclass(frozen=True)
class SeqData:
    """One data frame: per-link monotonic sequence number + payload."""

    seq: Any
    msg: Any


@wire("RsAck")
@dataclasses.dataclass(frozen=True)
class ResumeAck:
    """Periodic cumulative ack (every ``_ACK_EVERY`` delivered frames)
    letting the sender trim its replay buffer in steady state."""

    seq: Any


@wire("StReq")
@dataclasses.dataclass(frozen=True)
class SnapReq:
    """Joiner → peers: request state-transfer metadata for epochs
    ``[from_epoch, upto_epoch]`` (``upto_epoch=None`` in the probe
    round means "up to whatever you have committed"), or — with
    ``fetch=True``, sent to exactly one quorum-agreeing provider — the
    chunk stream itself."""

    from_epoch: Any
    upto_epoch: Any
    fetch: Any


@wire("StMeta")
@dataclasses.dataclass(frozen=True)
class SnapMeta:
    """Provider → joiner: the snapshot it can serve for the requested
    range — payload digest, total size, and chunk count.  The joiner
    installs a payload only when f+1 peers agree on this tuple."""

    from_epoch: Any
    upto_epoch: Any
    digest: Any
    size: Any
    chunks: Any


@wire("StChunk")
@dataclasses.dataclass(frozen=True)
class SnapChunk:
    """One in-order slice of the snapshot payload.  ``index`` and
    ``offset`` are attacker-controlled and strictly validated against
    the quorum-pinned meta — out-of-order, overlapping or oversized
    chunks fault the provider and never grow the receive buffer."""

    index: Any
    offset: Any
    data: Any


@wire("StDone")
@dataclasses.dataclass(frozen=True)
class SnapDone:
    """End of the chunk stream; the joiner verifies the reassembled
    payload's digest against the f+1 quorum before decoding a byte."""

    upto_epoch: Any
    digest: Any


@wire("ObTrace")
@dataclasses.dataclass(frozen=True)
class ObTrace:
    """Observability piggyback (fleet-telemetry PR): the sender's
    trace context — node id, its outbound trace sequence number, and
    the highest epoch it has committed — carried as an unsequenced
    control frame in the existing control plane (additive and
    manifest-append-only; data frames are unchanged).  The receiver
    emits a ``trace_link`` row, giving ``obs.timeline`` an explicit
    cross-process causal edge even when the two nodes' traces live in
    separate files.  Every field is attacker-controlled: malformed
    contexts are attributed (``FaultKind.INVALID_MESSAGE`` +
    ``wire.bad_obtrace``), never crash the pump, and never reach the
    algorithm."""

    node: Any
    seq: Any
    epoch: Any


_ST_TYPES = (SnapReq, SnapMeta, SnapChunk, SnapDone)

# A malicious peer can mint one fault attribution per malformed frame,
# so the fault log is the one list on the serving plane that grows at
# attacker rate.  Keep the most recent window; older attributions have
# already been counted in the obs counters.
_FAULT_LOG_MAX = 4096


def _seq_ok(v: Any) -> bool:
    """Total validator for wire sequence numbers (bool is an int —
    reject it explicitly)."""
    return isinstance(v, int) and not isinstance(v, bool) and 0 <= v < _MAX_SEQ


def generate_keys_for(addresses: List[str], our_addr: str) -> NetworkInfo:
    """Placeholder key dealing (INSECURE — demo/test only, like the
    reference's placeholder keys): every node derives the identical
    mock key set deterministically from the sorted address list."""
    ids = sorted(addresses)
    rng = random.Random("hbbft_tpu-tcp|" + "|".join(ids))
    netinfos = NetworkInfo.generate_map(ids, rng, mock=True)
    return netinfos[our_addr]


async def _read_frame(reader: asyncio.StreamReader) -> Any:
    message, _ = await _read_frame_sized(reader)
    return message


async def _read_frame_sized(reader: asyncio.StreamReader) -> Any:
    """→ (message, frame length in payload bytes)."""
    header = await reader.readexactly(_LEN_BYTES)
    length = int.from_bytes(header, "big")
    if length > _MAX_FRAME:
        raise ConnectionError(f"oversized frame: {length} bytes")
    return loads(await reader.readexactly(length)), length


def _frame(message: Any) -> bytes:
    payload = dumps(message)
    return len(payload).to_bytes(_LEN_BYTES, "big") + payload


class TcpNode:
    """One consensus node: an algorithm instance wired to its peers over
    TCP (reference ``Node::run``, ``node.rs:60-137``).

    **Security note (demo transport only)**: peer identity in the
    handshake is self-reported and unauthenticated — any socket that
    can reach the listener may claim any address in ``peer_addrs``
    (exactly like the reference example's plain-TCP handshake,
    ``connection.rs:20-47``).  A handshake for an address that is
    already connected is rejected (no impostor can displace a live
    link), but production use requires an authenticated transport
    (TLS, or a signature over the handshake with the peer's known
    public key)."""

    def __init__(
        self,
        our_addr: str,
        peer_addrs: List[str],
        new_algo: Callable[[NetworkInfo], Any],
        netinfo: Optional[NetworkInfo] = None,
        dial_retries: int = 50,
        resume_recv: Optional[Dict[str, int]] = None,
        resume_send: Optional[Dict[str, int]] = None,
        replay_max_frames: Optional[int] = None,
        replay_max_bytes: Optional[int] = None,
    ):
        self.our_addr = our_addr
        self.dial_retries = dial_retries
        self.peer_addrs = sorted(set(peer_addrs) - {our_addr})
        self.all_addrs = sorted(self.peer_addrs + [our_addr])
        self.netinfo = netinfo or generate_keys_for(self.all_addrs, our_addr)
        self.algo = new_algo(self.netinfo)
        self.outputs: List[Any] = []
        self.faults: List[Any] = []
        # Optional synchronous observer invoked once per algorithm
        # output (e.g. the serving gateway's commit-ack watcher); a
        # misbehaving hook must not take down the protocol pump.
        self.on_output: Optional[Callable[[Any], None]] = None
        # Optional hook invoked after each pump iteration routes its
        # step — the quiescent point where the restart driver writes
        # epoch checkpoints (algorithm state and send seqs consistent).
        self.on_step: Optional[Callable[["TcpNode"], None]] = None
        self._writers: Dict[str, asyncio.StreamWriter] = {}
        self._inbox: asyncio.Queue = asyncio.Queue()
        # Serializes algorithm access across the pump, input(), and the
        # catch-up installer now that handler calls run on executor
        # threads: the lock is held across a whole handle+route+ack
        # iteration, preserving the atomicity the single-threaded loop
        # used to provide (e.g. _send_seq mutation in _route vs. the
        # on_step hook's read of it).
        self._algo_lock = asyncio.Lock()
        self._server: Optional[asyncio.base_events.Server] = None
        self._tasks: List[asyncio.Task] = []
        self._connected = asyncio.Event()
        self._closing = False
        # session-resumption state (restart: seed from checkpoint meta
        # + WAL so numbering continues the pre-crash stream exactly)
        self._send_seq: Dict[str, int] = dict(resume_send or {})
        self._recv_seq: Dict[str, int] = dict(resume_recv or {})
        self._replay: Dict[str, Deque[Tuple[int, bytes]]] = {}
        self._replay_bytes: Dict[str, int] = {}
        # Acks must reflect the *applied* high-water mark, not the
        # delivered one: a durable algorithm WAL-logs a frame only when
        # it is handled, and an ack for a delivered-but-unapplied frame
        # would let the peer trim it — a crash before apply would then
        # lose it forever.  The recv loop records each delivered frame's
        # seq here; the pump acks as it consumes them (FIFO per peer).
        self._seq_trail: Dict[str, Deque[int]] = {}
        self._applied_since_ack: Dict[str, int] = {}
        # Applied (not merely delivered) inbound high-water mark per
        # peer — what a durable checkpoint may claim as its resume
        # seqs.  Starts at the resume point (everything recovered from
        # the WAL is applied by definition) and advances as the pump
        # consumes frames; a state-transfer install jumps it over the
        # evicted range.
        self._applied_seq: Dict[str, int] = dict(resume_recv or {})
        # replay-buffer bounds: per-node overrides let tests and the
        # dark-peer scenarios force eviction without routing 4096 frames
        self._replay_max_frames = (
            _REPLAY_MAX_FRAMES if replay_max_frames is None
            else max(1, int(replay_max_frames))
        )
        self._replay_max_bytes = (
            _REPLAY_MAX_BYTES if replay_max_bytes is None
            else max(1, int(replay_max_bytes))
        )
        # State-transfer hook (``recover/transfer.py``): the restart
        # driver attaches a CatchupManager here.  None keeps the legacy
        # behaviour — an evicted replay range is a loudly-counted,
        # permanently severed stream.
        self.transfer: Optional[Any] = None
        # fleet-telemetry trace piggyback: our outbound ObTrace
        # counter and the highest epoch this node has committed (what
        # the piggyback advertises to peers)
        self._ob_seq = 0
        self._ob_epoch: Optional[int] = None
        if _TRACK_NODE is not None:
            _TRACK_NODE(self)

    @property
    def send_seqs(self) -> Dict[str, int]:
        """Snapshot of per-peer outbound sequence numbers — stored in
        checkpoint meta so a restarted node renumbers continuously."""
        return dict(self._send_seq)

    @property
    def applied_seqs(self) -> Dict[str, int]:
        """Snapshot of per-peer *applied* inbound sequence numbers —
        the resume high-water mark a checkpoint may safely claim (a
        delivered-but-unapplied frame is never included)."""
        return dict(self._applied_seq)

    def send_control(self, peer: str, message: Any) -> bool:
        """Write an unsequenced control frame (the state-transfer
        plane) to a live link.  Control frames are never buffered or
        replayed — the transfer layer owns retries.  Returns ``False``
        when the link is down."""
        w = self._writers.get(peer)
        if w is None:
            return False
        w.write(_frame(message))
        return True

    # -- connection management --------------------------------------------

    async def start(self, mesh_timeout: Optional[float] = None) -> None:
        """Bind our listener, dial every larger-address peer (the
        smaller address always dials — one connection per pair), and
        block until the full mesh is up.

        ``mesh_timeout``: overall deadline in seconds for the mesh to
        complete; ``ConnectionError`` on expiry instead of waiting
        forever (a dialed peer that registered and then dropped is
        tolerated like any silent node — only *failed dials* and the
        deadline abort startup)."""
        deadline = (
            None
            if mesh_timeout is None
            else asyncio.get_event_loop().time() + mesh_timeout
        )
        host, port = self.our_addr.rsplit(":", 1)
        self._server = await asyncio.start_server(
            self._on_accept, host, int(port)
        )
        # we dial every peer with a larger address; they dial us
        for peer in self.peer_addrs:
            if self.our_addr < peer:
                self._tasks.append(
                    asyncio.ensure_future(self._dial(peer))
                )
        # wait for the mesh, surfacing dial failures instead of hanging
        waiter = asyncio.ensure_future(self._connected.wait())
        pending = set(self._tasks)
        try:
            while not self._connected.is_set():
                wait_for = None
                if deadline is not None:
                    wait_for = deadline - asyncio.get_event_loop().time()
                    if wait_for <= 0:
                        raise ConnectionError(
                            f"mesh incomplete after {mesh_timeout}s "
                            f"({len(self._writers)}/{len(self.peer_addrs)} "
                            "links up)"
                        )
                done, _ = await asyncio.wait(
                    {waiter} | pending,
                    return_when=asyncio.FIRST_COMPLETED,
                    timeout=wait_for,
                )
                for t in done:
                    if t is waiter:
                        continue
                    pending.discard(t)
                    exc = t.exception()
                    if exc is not None:
                        raise exc
        finally:
            if not waiter.done():
                waiter.cancel()

    async def _dial(self, peer: str) -> None:
        host, port = peer.rsplit(":", 1)
        # initial connect: bounded retries so start() fails fast on an
        # unreachable peer instead of hanging the mesh
        for attempt in range(self.dial_retries):
            try:
                reader, writer = await asyncio.open_connection(host, int(port))
                break
            except OSError:
                await asyncio.sleep(0.05 * (attempt + 1))
        else:
            raise ConnectionError(f"could not reach peer {peer}")
        await self._run_link(peer, reader, writer)
        # The link died.  The dial side owns reconnection: redial with
        # jittered exponential backoff until close() — a validator
        # restarting after a crash comes back on the same address.
        backoff = _REDIAL_BASE_S
        while not self._closing:
            try:
                reader, writer = await asyncio.open_connection(host, int(port))
            except OSError:
                await asyncio.sleep(backoff * (0.5 + random.random()))
                backoff = min(backoff * 2.0, _REDIAL_CAP_S)
                continue
            backoff = _REDIAL_BASE_S
            await self._run_link(peer, reader, writer)

    async def _run_link(
        self,
        peer: str,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
    ) -> None:
        """Dial-side resume handshake, then the receive loop, on one
        connection.  Returns when the link dies."""
        try:
            writer.write(
                _frame(ResumeHello(self.our_addr, self._recv_seq.get(peer, 0)))
            )
            await writer.drain()
            welcome = await _read_frame(reader)
        except (
            asyncio.IncompleteReadError,
            ConnectionError,
            OSError,
            SerializationError,
        ):
            writer.close()
            return
        if not isinstance(welcome, ResumeWelcome) or not _seq_ok(
            welcome.recv_seq
        ):
            rec = _obs.ACTIVE
            if rec is not None:
                rec.count("wire.bad_resume")
            writer.close()
            return
        self._resume_link(peer, welcome.recv_seq, writer)
        try:
            await writer.drain()
        except (ConnectionError, OSError):
            writer.close()
            return
        self._register(peer, writer)
        try:
            await self._recv_loop(peer, reader)
        finally:
            self._unregister(peer, writer)

    async def _on_accept(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        try:
            hello = await _read_frame(reader)
        except (
            asyncio.IncompleteReadError,
            ConnectionError,
            SerializationError,
        ):
            writer.close()
            return
        if isinstance(hello, ResumeHello):
            peer, peer_recv = hello.addr, hello.recv_seq
            if not _seq_ok(peer_recv):
                rec = _obs.ACTIVE
                if rec is not None:
                    rec.count("wire.bad_resume")
                writer.close()
                return
        else:
            # legacy handshake: a bare address frame, no resume state
            peer, peer_recv = hello, None
        if (
            not isinstance(peer, (str, int))
            or peer not in self.peer_addrs
            or peer in self._writers
        ):
            # a non-id handshake payload (the wire can carry anything,
            # including an unhashable value that would TypeError the
            # membership tests), an unknown claim, or an impostor
            # claiming a peer whose link
            # is already LIVE — reject rather than displace the writer.
            # (Dead links are unregistered on recv-loop exit, so a
            # legitimately restarted peer can always re-handshake; a
            # peer reconnecting FASTER than its stale link's EOF is
            # observed gets refused once and must retry — the dial
            # side's redial loop absorbs the refusal and retries.)
            writer.close()
            return
        if peer_recv is not None:
            try:
                writer.write(
                    _frame(ResumeWelcome(self._recv_seq.get(peer, 0)))
                )
                await writer.drain()
            except (ConnectionError, OSError):
                writer.close()
                return
            self._resume_link(peer, peer_recv, writer)
            try:
                await writer.drain()
            except (ConnectionError, OSError):
                writer.close()
                return
        self._register(peer, writer)
        try:
            await self._recv_loop(peer, reader)
        finally:
            self._unregister(peer, writer)

    def _resume_link(
        self, peer: str, peer_recv: int, writer: asyncio.StreamWriter
    ) -> None:
        """Trim the replay buffer to the peer's consumed high-water
        mark and queue the remainder for re-send (the frames it may
        never have seen).  ``peer_recv`` is attacker-controlled: it is
        bounds-checked by the caller and only *selects a trim point* —
        it never sizes an allocation."""
        if not isinstance(peer_recv, int) or isinstance(peer_recv, bool):
            return
        if peer_recv < 0 or peer_recv >= _MAX_SEQ:
            return
        buf = self._replay.get(peer)
        dropped = replayed = 0
        rec = _obs.ACTIVE
        if buf:
            while buf and buf[0][0] <= peer_recv:
                _, frame = buf.popleft()
                self._replay_bytes[peer] = (
                    self._replay_bytes.get(peer, 0) - len(frame)
                )
                dropped += 1
            if buf and buf[0][0] > peer_recv + 1 and rec is not None:
                # the peer fell behind our replay buffer: the frames
                # below buf[0] were evicted and are gone — it will see
                # the gap on the first replayed frame and must
                # state-transfer to catch up
                rec.count("wire.resume_gap")
                rec.count(f"wire.resume_gap.{peer}")
            for _, frame in buf:
                writer.write(frame)
                replayed += 1
        if rec is not None:
            rec.event(
                "wire_resume",
                peer=peer,
                replayed=replayed,
                dropped=dropped,
                recv_seq=peer_recv,
            )
            if replayed:
                rec.count("wire.replayed_frames", replayed)

    def _register(self, peer: str, writer: asyncio.StreamWriter) -> None:
        self._writers[peer] = writer
        if len(self._writers) == len(self.peer_addrs):
            self._connected.set()

    def _unregister(self, peer: str, writer: asyncio.StreamWriter) -> None:
        """Drop a dead link so the peer can reconnect (only if it is
        still the registered writer — a newer link is left alone)."""
        if self._writers.get(peer) is writer:
            del self._writers[peer]

    # -- replay buffer ------------------------------------------------------

    def _buffer_frame(self, peer: str, seq: int, frame: bytes) -> None:
        """Hold an outbound frame until the peer acks past it.  Bounded
        by our own caps; eviction severs resume-exactness for the
        evicted frames and is therefore counted loudly."""
        buf = self._replay.setdefault(peer, deque())
        buf.append((seq, frame))
        self._replay_bytes[peer] = self._replay_bytes.get(peer, 0) + len(frame)
        evicted = 0
        while len(buf) > self._replay_max_frames or (
            self._replay_bytes[peer] > self._replay_max_bytes and len(buf) > 1
        ):
            _, old = buf.popleft()
            self._replay_bytes[peer] -= len(old)
            evicted += 1
        if evicted:
            rec = _obs.ACTIVE
            if rec is not None:
                rec.count("wire.replay_evicted", evicted)
                rec.count(f"wire.replay_evicted.{peer}", evicted)

    def _trim_acked(self, peer: str, seq: int) -> None:
        buf = self._replay.get(peer)
        if not buf:
            return
        while buf and buf[0][0] <= seq:
            _, frame = buf.popleft()
            self._replay_bytes[peer] = (
                self._replay_bytes.get(peer, 0) - len(frame)
            )

    def _note_fault(self, peer: str, kind: FaultKind) -> None:
        self.faults.append(Fault(peer, kind))
        if len(self.faults) > _FAULT_LOG_MAX:
            del self.faults[: len(self.faults) - _FAULT_LOG_MAX]

    async def _recv_loop(self, peer: str, reader: asyncio.StreamReader) -> None:
        while True:
            try:
                message, size = await _read_frame_sized(reader)
            except (asyncio.IncompleteReadError, ConnectionError, OSError):
                return  # peer closed; the protocol tolerates f silent nodes
            except SerializationError:
                continue  # malformed frame: drop it, the length-prefixed
                # stream stays aligned on the next frame
            rec = _obs.ACTIVE
            if isinstance(message, ResumeAck):
                if _seq_ok(message.seq):
                    self._trim_acked(peer, message.seq)
                elif rec is not None:
                    rec.count("wire.bad_resume")
                continue
            if isinstance(message, (ResumeHello, ResumeWelcome)):
                # resume control frames are only meaningful as the
                # first exchange on a link — mid-stream ones are noise
                if rec is not None:
                    rec.count("wire.unexpected_resume")
                continue
            if isinstance(message, ObTrace):
                # trace piggyback: every field is attacker-controlled.
                # A malformed context is attributed, never fatal; a
                # valid one becomes the cross-process causal edge.
                ep = message.epoch
                if (
                    isinstance(message.node, (str, int))
                    and not isinstance(message.node, bool)
                    and _seq_ok(message.seq)
                    and (ep is None or _seq_ok(ep))
                ):
                    if rec is not None:
                        rec.count("wire.obtrace")
                        if ep is None:
                            rec.event(
                                "trace_link",
                                node=self.our_addr,
                                peer=message.node,
                                seq=message.seq,
                            )
                        else:
                            rec.event(
                                "trace_link",
                                node=self.our_addr,
                                peer=message.node,
                                seq=message.seq,
                                epoch=ep,
                            )
                else:
                    self._note_fault(peer, FaultKind.INVALID_MESSAGE)
                    if rec is not None:
                        rec.count("wire.bad_obtrace")
                continue
            if isinstance(message, _ST_TYPES):
                # state-transfer control plane: unsequenced, handled by
                # the attached CatchupManager.  A node without one (or
                # a manager error) drops the frame — never the loop.
                if self.transfer is None:
                    if rec is not None:
                        rec.count("wire.st_unexpected")
                    continue
                try:
                    await self.transfer.on_control(peer, message)
                except Exception:
                    if rec is not None:
                        rec.count("wire.st_errors")
                continue
            if isinstance(message, SeqData):
                if not _seq_ok(message.seq):
                    if rec is not None:
                        rec.count("wire.bad_seq")
                    continue
                last = self._recv_seq.get(peer, 0)
                if message.seq <= last:
                    # duplicate delivery (replay overlap after resume,
                    # or a misbehaving peer) — exactly-once by drop
                    if rec is not None:
                        rec.count("wire.dup_frames")
                    continue
                if message.seq > last + 1:
                    # frames [last+1, seq-1] were evicted from the
                    # peer's replay buffer while we were dark.  With a
                    # CatchupManager attached this escalates into a
                    # state transfer instead of a severed stream.
                    if rec is not None:
                        rec.count("wire.seq_gap")
                    if self.transfer is not None:
                        try:
                            await self.transfer.on_gap(
                                peer, last, message.seq
                            )
                        except Exception:
                            if rec is not None:
                                rec.count("wire.st_errors")
                self._recv_seq[peer] = message.seq
                self._seq_trail.setdefault(peer, deque()).append(message.seq)
                recv_seq: Optional[int] = message.seq
                message = message.msg
            else:
                # legacy bare frame (pre-resume peer): no seq to ack
                self._seq_trail.setdefault(peer, deque()).append(0)
                recv_seq = None
            if rec is not None:
                # v2 causal-join fields: the receiving endpoint + the
                # link seq, matching the sender's wire_send row
                if recv_seq is None:
                    rec.event(
                        "wire_recv", peer=peer, size=size, node=self.our_addr
                    )
                else:
                    rec.event(
                        "wire_recv",
                        peer=peer,
                        size=size,
                        node=self.our_addr,
                        seq=recv_seq,
                    )
                rec.count("wire.recv_frames")
                rec.count("wire.recv_bytes", size)
            if self.transfer is not None and self.transfer.holding():
                # a state transfer is in flight: data frames delivered
                # now refer to epochs the snapshot supersedes or to
                # live epochs we cannot process yet — parked in arrival
                # order and flushed to the inbox at install time
                self.transfer.hold(peer, message)
                continue
            await self._inbox.put((peer, message))

    def _ack_applied(self, sender: str) -> None:
        """Called by the pump once per consumed inbound frame: the
        frame is now applied (and, for a durable algorithm, WAL-logged
        *before* apply), so its seq is safe to ack — the peer may trim
        its replay buffer up to here without a crash losing anything."""
        if not isinstance(sender, str):
            return
        trail = self._seq_trail.get(sender)
        if not trail:
            return
        seq = trail.popleft()
        if not seq:
            return  # legacy bare frame — nothing to ack
        self._applied_seq[sender] = seq
        n = self._applied_since_ack.get(sender, 0) + 1
        if n >= _ACK_EVERY:
            n = 0
            w = self._writers.get(sender)
            if w is not None:
                w.write(_frame(ResumeAck(seq)))
        self._applied_since_ack[sender] = n

    # -- the protocol pump --------------------------------------------------

    async def _route(self, step: Step) -> None:
        rec = _obs.ACTIVE
        for out in step.output:
            # grows one entry per *committed* batch — consensus-rate,
            # behind a full agreement round, not attacker-rate — and is
            # the return value of run()  # lint: ok(bounded-state)
            self.outputs.append(out)
            ep = getattr(out, "epoch", None)
            if type(ep) is int:
                # one committed batch on this node — the decrypt→commit
                # hop of the fleet timeline, and the epoch the ObTrace
                # piggyback advertises from here on
                self._ob_epoch = ep
                if rec is not None:
                    txs = 0
                    contrib = getattr(out, "contributions", None)
                    if isinstance(contrib, dict):
                        for c in contrib.values():
                            txs += len(c) if isinstance(c, (list, tuple)) else 1
                    rec.event(
                        "node_commit", node=self.our_addr, epoch=ep, txs=txs
                    )
                    rec.set_epoch(ep)
            if self.on_output is not None:
                try:
                    self.on_output(out)
                except Exception:
                    if rec is not None:
                        rec.count("wire.output_hook_errors")
        self.faults.extend(step.fault_log)
        if len(self.faults) > _FAULT_LOG_MAX:
            del self.faults[: len(self.faults) - _FAULT_LOG_MAX]
        touched = []
        for tm in step.messages:
            if tm.target.is_all:
                targets = self.peer_addrs
            else:
                targets = [tm.target.node] if tm.target.node != self.our_addr else []
            kind = "all" if tm.target.is_all else "node"
            for peer in targets:
                # every data frame is sequenced + buffered, whether or
                # not the link is currently up — a down peer's frames
                # wait in the replay buffer for its resume handshake
                seq = self._send_seq.get(peer, 0) + 1
                self._send_seq[peer] = seq
                frame = _frame(SeqData(seq, tm.message))
                self._buffer_frame(peer, seq, frame)
                w = self._writers.get(peer)
                if w is not None:
                    w.write(frame)
                    touched.append((peer, w))
                    if rec is not None:
                        rec.event(
                            "wire_send",
                            peer=peer,
                            size=len(frame) - _LEN_BYTES,
                            kind=kind,
                            node=self.our_addr,
                            seq=seq,
                        )
                        rec.count("wire.sent_frames")
                        rec.count("wire.sent_bytes", len(frame) - _LEN_BYTES)
        if rec is not None and touched:
            # piggyback our trace context once per touched peer per
            # routing round — an unsequenced control frame, so it is
            # never buffered/replayed and costs nothing when idle
            self._ob_seq += 1
            ob = ObTrace(self.our_addr, self._ob_seq, self._ob_epoch)
            for peer in {p for p, _ in touched}:
                self.send_control(peer, ob)
        for peer, w in touched:
            try:
                await w.drain()
            except (ConnectionError, OSError):
                # The link died under the write.  The frame is safe in
                # the replay buffer and will be re-sent on resume —
                # but never swallow the drop invisibly: attribute it.
                if rec is not None:
                    rec.count("wire.send_drops")
                    rec.count(f"wire.send_drops.{peer}")

    async def input(self, value: Any) -> None:
        # handle_input runs threshold crypto (batch encryption) and,
        # for durable nodes, a WAL fsync — offload it so the event loop
        # keeps serving sockets.  The lock keeps the handle+route pair
        # atomic with respect to the pump.
        loop = asyncio.get_event_loop()
        async with self._algo_lock:
            step = await loop.run_in_executor(
                None, self.algo.handle_input, value
            )
            await self._route(step)

    async def run(
        self,
        until: Optional[Callable[["TcpNode"], bool]] = None,
        timeout: Optional[float] = None,
    ) -> List[Any]:
        """Pump messages until ``until(self)`` (default: the algorithm
        terminates).  Returns the collected outputs."""
        done = until or (lambda node: node.algo.terminated())
        loop = asyncio.get_event_loop()
        deadline = loop.time() + timeout if timeout is not None else None
        while not done(self):
            get = self._inbox.get()
            if deadline is not None:
                remaining = deadline - loop.time()
                if remaining <= 0:
                    raise asyncio.TimeoutError("consensus did not finish")
                sender, message = await asyncio.wait_for(get, remaining)
            else:
                sender, message = await get
            # The handler runs threshold crypto (combine/verify) and,
            # for durable nodes, a WAL fsync — park it on an executor
            # thread so one slow message never stalls the recv loops.
            # The lock spans the whole handle+route+ack iteration: the
            # single-threaded loop used to make _route's _send_seq
            # writes atomic w.r.t. the on_step checkpoint hook, and the
            # offload must not reintroduce that race.
            async with self._algo_lock:
                try:
                    step = await loop.run_in_executor(
                        None, self.algo.handle_message, sender, message
                    )
                except Exception:
                    # A deserializable-but-malformed message slipped
                    # past the handler's own guards.  Never crash the
                    # pump on remote input — but never drop it silently
                    # either: attribute it so the failure is visible in
                    # faults + obs counters.
                    self._note_fault(sender, FaultKind.INVALID_MESSAGE)
                    rec = _obs.ACTIVE
                    if rec is not None:
                        rec.count("wire.handler_errors")
                    self._ack_applied(sender)
                    continue
                await self._route(step)
                self._ack_applied(sender)
                if self.on_step is not None:
                    # The restart driver's hook writes epoch
                    # checkpoints (WAL append + fsync) — same offload.
                    # Still inside the lock, so its view of _send_seq
                    # is quiescent.
                    try:
                        await loop.run_in_executor(None, self.on_step, self)
                    except Exception:
                        rec = _obs.ACTIVE
                        if rec is not None:
                            rec.count("wire.output_hook_errors")
        return self.outputs

    async def close(self) -> None:
        self._closing = True
        for t in self._tasks:
            t.cancel()
        self._tasks.clear()
        for w in self._writers.values():
            w.close()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
