"""Byzantine-safe state transfer — rejoining past the replay bound.

A validator dark longer than the transport's replay-buffer bound
(``transport/tcp.py``: ``_REPLAY_MAX_FRAMES`` / ``_REPLAY_MAX_BYTES``)
can never be caught up by frame replay: its peers evicted the frames it
missed.  Before this module that was a loud counter and a permanently
severed stream.  Now the lagging node fetches an *epoch snapshot* — the
committed batches it missed — from its peers and fast-forwards:

::

    joiner                                peers (n-1, ≤ f Byzantine)
      |-- StReq(from, None, fetch=False) --->|   probe: what can you serve?
      |<-- StMeta(from, upto, digest, ...) --|   one per peer
      |          (no f+1 agreement? pin the (f+1)-th highest upto
      |           and re-request the exact range)
      |-- StReq(from, E, fetch=True) ------->|   to ONE quorum provider
      |<-- StChunk(i, off, data) * k --------|   strict in-order slices
      |<-- StDone(E, digest) ----------------|
      verify sha256(payload) == quorum digest
      install_snapshot(E, batches)  →  rejoin live at epoch E+1

The Byzantine argument: honest HoneyBadger validators commit *identical*
batches per epoch, and the snapshot payload is their canonical encoding
(``core.serialize.dumps`` — deterministic, dict keys sorted), so every
honest peer serves byte-identical payloads for the same range.  With at
most f Byzantine peers, f+1 matching ``(range, digest, size, chunks)``
tuples therefore include at least one honest peer — the agreed digest
IS the honest payload's digest.  A Byzantine provider can still join
the quorum with the honest digest and then serve forged bytes, but the
reassembled payload is hashed before a single byte is decoded: the
mismatch is attributed (``FaultKind.INVALID_SNAPSHOT``), the provider
is excluded, and the fetch retries against the next quorum peer.  A
forged snapshot is never applied.

Taint discipline (the ``wire-taint`` rule covers this module): chunk
``size``/``offset``/``index`` fields are attacker-controlled alloc-sink
roots.  The manager bounds the accepted payload by ``_ST_MAX_BYTES``
*before* accepting any chunk, accumulates received bytes rather than
pre-allocating from a claimed size, and rejects out-of-order,
overlapping, or oversized chunks with a fault — a hostile provider can
never grow the receive buffer past the quorum-pinned size.

While a transfer is in flight the transport parks inbound data frames
(``CatchupManager.hold``) and flushes them to the inbox after install —
late frames for snapshot-covered epochs are dropped by the algorithm's
obsolete-epoch check, frames for live epochs apply normally, and the
WAL sees them *after* the install checkpoint so crash recovery replays
the exact same order.
"""

from __future__ import annotations

import asyncio
import hashlib
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from ..core.fault import FaultKind, FaultLog
from ..core.serialize import SerializationError, dumps, loads
from ..obs import recorder as _obs
from ..transport import tcp as _tcp
from ..transport.tcp import SnapChunk, SnapDone, SnapMeta, SnapReq

_MAX_EPOCH = 2**62
# full probe→pin→fetch restarts before giving up (each restart already
# excludes every provider that served garbage)
_MAX_RESTARTS = 3


def _epoch_ok(v: Any) -> bool:
    """Total validator for wire epoch numbers."""
    return isinstance(v, int) and not isinstance(v, bool) and 0 <= v < _MAX_EPOCH


def _int_ok(v: Any) -> bool:
    return isinstance(v, int) and not isinstance(v, bool) and v >= 0


def encode_snapshot(batches: List[Any]) -> bytes:
    """Canonical snapshot payload: the wire codec over the batch list.
    Deterministic (dict keys sorted), so honest providers serving the
    same committed range produce byte-identical payloads — the basis of
    the f+1 digest quorum."""
    return dumps(list(batches))


def snapshot_digest(payload: bytes) -> bytes:
    return hashlib.sha256(payload).digest()


class SnapshotStore:
    """Provider-side retention of committed batches, keyed by epoch.

    Bounded: at most ``retain`` epochs are kept (oldest evicted), which
    also bounds the range any single ``StReq`` can make us encode."""

    def __init__(self, retain: int = 1024):
        self.retain = max(1, int(retain))
        self._batches: Dict[int, Any] = {}
        self._high = -1

    def record(self, output: Any) -> None:
        """Feed one algorithm output; non-batch outputs are ignored."""
        epoch = getattr(output, "epoch", None)
        if not _epoch_ok(epoch):
            return
        self._batches[epoch] = output
        if epoch > self._high:
            self._high = epoch
        while len(self._batches) > self.retain:
            del self._batches[min(self._batches)]

    def high(self) -> int:
        """Highest recorded epoch (-1 when empty)."""
        return self._high

    def slice(self, from_epoch: int, upto_epoch: int) -> Optional[List[Any]]:
        """The contiguous batches for ``[from_epoch, upto_epoch]``, or
        ``None`` when any epoch in the range is missing.  The caller
        bounds the span (≤ ``retain``) before we iterate."""
        out = []
        for e in range(from_epoch, upto_epoch + 1):
            b = self._batches.get(e)
            if b is None:
                return None
            out.append(b)
        return out

    def __len__(self) -> int:
        return len(self._batches)


class CatchupManager:
    """The ``TcpNode.transfer`` hook: provider and joiner in one object.

    Provider role: answers ``StReq`` from the :class:`SnapshotStore`
    (silence when we cannot serve the range — the joiner's quorum
    simply doesn't count us).  Joiner role: driven by the transport's
    gap detection, runs probe → pin → fetch → verify → install and owns
    the parked-frame buffer while the transfer is in flight."""

    IDLE = "idle"
    PROBE = "probe"
    FETCH = "fetch"

    def __init__(
        self,
        node: Any,
        num_faulty: int,
        store: Optional[SnapshotStore] = None,
        install_fn: Optional[Callable[[int, List[Any]], Any]] = None,
        epoch_fn: Optional[Callable[[], int]] = None,
    ):
        self.node = node
        self.f = max(0, int(num_faulty))
        self.store = store if store is not None else SnapshotStore()
        # install defaults to the DurableAlgo surface; epoch to the
        # wrapped algorithm's current epoch
        self._install_fn = install_fn
        self._epoch_fn = epoch_fn or (
            lambda: int(getattr(self.node.algo, "epoch", 0))
        )
        # Install runs a durable checkpoint (WAL append + fsync) — it
        # is offloaded to an executor thread under the node's algorithm
        # lock so it serializes with the pump.  Tests drive this class
        # with bare fakes, hence the fallback lock.
        self._lock = getattr(node, "_algo_lock", None) or asyncio.Lock()
        self.state = self.IDLE
        self.installed = 0  # completed transfers (tests/scenarios)
        self._from = 0
        self._target: Optional[int] = None
        # peer -> (upto, digest, size, chunks) offers (probe + pin)
        self._offers: Dict[str, Tuple[int, bytes, int, int]] = {}
        # peers replying "nothing newer than your epoch" (empty offer)
        self._empty_votes: Set[str] = set()
        self._pinned = False
        self._failed: Set[str] = set()
        self._quorum_peers: List[str] = []
        self._provider: Optional[str] = None
        self._expect: Optional[Tuple[bytes, int, int]] = None
        self._parts: List[bytes] = []
        self._got = 0
        self._next_idx = 0
        self._restarts = 0
        # parked inbound data frames, global arrival order
        self._held: List[Tuple[str, Any]] = []
        self._held_first: Dict[str, int] = {}

    # -- transport-facing hooks -----------------------------------------

    def holding(self) -> bool:
        return self.state != self.IDLE

    def hold(self, peer: str, message: Any) -> None:
        """Park one delivered data frame until install flushes it."""
        self._held_first.setdefault(peer, self.node._recv_seq.get(peer, 0))
        self._held.append((peer, message))

    async def on_gap(self, peer: str, last: int, seq: int) -> None:
        """The transport saw seqs jump ``last → seq`` on this link —
        the frames between were evicted from the peer's replay buffer."""
        rec = _obs.ACTIVE
        if rec is not None:
            rec.count("st.gap")
        if self.state != self.IDLE:
            # a second eviction on a link mid-transfer punches a hole
            # in its parked stream: drop that peer's parked frames (the
            # same loss class the snapshot already covers) and rebase
            if peer in self._held_first:
                self._held = [(p, m) for (p, m) in self._held if p != peer]
                del self._held_first[peer]
            elif self.state == self.PROBE and peer not in self._offers:
                # a resumed link coming up AFTER the probe broadcast
                # missed its SnapReq (send_control to a down link is
                # lost) — its first replayed frame gaps here, so probe
                # it directly; a slow mesh still reaches f+1 offers
                self.node.send_control(
                    peer,
                    SnapReq(
                        self._from,
                        self._target if self._pinned else None,
                        False,
                    ),
                )
            return
        self._restarts = 0
        self._begin_probe()

    async def on_control(self, peer: str, message: Any) -> None:
        if isinstance(message, SnapReq):
            self._serve(peer, message)
        elif isinstance(message, SnapMeta):
            self._on_meta(peer, message)
        elif isinstance(message, SnapChunk):
            await self._on_chunk(peer, message)
        elif isinstance(message, SnapDone):
            await self._on_done(peer, message)

    # -- provider role ---------------------------------------------------

    def _serve(self, peer: str, req: SnapReq) -> None:
        if (
            not _epoch_ok(req.from_epoch)
            or not isinstance(req.fetch, bool)
            or not (req.upto_epoch is None or _epoch_ok(req.upto_epoch))
        ):
            self._attribute(peer, "bad-req")
            return
        upto = self.store.high() if req.upto_epoch is None else req.upto_epoch
        if upto < req.from_epoch:
            # nothing newer than the joiner already has: answer with an
            # explicit empty offer (sentinel digest=b"", size=chunks=0)
            # so f+1 such votes let it conclude the gap needs no
            # transfer, instead of staying silent and leaving it in
            # PROBE holding frames forever
            self.node.send_control(
                peer, SnapMeta(req.from_epoch, req.from_epoch, b"", 0, 0)
            )
            return
        if upto - req.from_epoch + 1 > self.store.retain:
            # a hostile width would make us encode an unbounded range
            self._attribute(peer, "range-too-wide")
            return
        batches = self.store.slice(req.from_epoch, upto)
        if batches is None:
            return  # a hole in our retention; stay silent
        payload = encode_snapshot(batches)
        if len(payload) > _tcp._ST_MAX_BYTES:
            return  # we cannot serve within the wire bound
        digest = snapshot_digest(payload)
        chunk = _tcp._ST_CHUNK_BYTES
        nchunks = max(1, (len(payload) + chunk - 1) // chunk)
        self.node.send_control(
            peer, SnapMeta(req.from_epoch, upto, digest, len(payload), nchunks)
        )
        if req.fetch:
            for i in range(nchunks):
                off = i * chunk
                self.node.send_control(
                    peer, SnapChunk(i, off, payload[off : off + chunk])
                )
            self.node.send_control(peer, SnapDone(upto, digest))
            rec = _obs.ACTIVE
            if rec is not None:
                rec.count("st.served")

    # -- joiner role -----------------------------------------------------

    def _begin_probe(self) -> None:
        self.state = self.PROBE
        self._from = int(self._epoch_fn())
        self._target = None
        self._offers.clear()
        self._empty_votes.clear()
        self._pinned = False
        self._provider = None
        self._expect = None
        self._reset_fetch()
        for p in self.node.peer_addrs:
            self.node.send_control(p, SnapReq(self._from, None, False))

    def _reset_fetch(self) -> None:
        self._parts = []
        self._got = 0
        self._next_idx = 0

    def _on_meta(self, peer: str, meta: SnapMeta) -> None:
        rec = _obs.ACTIVE
        if self.state != self.PROBE:
            if rec is not None:
                rec.count("st.unexpected")
            return
        if (
            meta.from_epoch == self._from
            and meta.upto_epoch == self._from
            and meta.digest == b""
            and meta.size == 0
            and meta.chunks == 0
        ):
            # explicit "nothing newer than your epoch" vote.  f+1 of
            # them include an honest peer at-or-behind us, so the gap
            # needs no snapshot (e.g. a single-link eviction, or a gap
            # that raced in right behind a completed install): stand
            # down and release the held frames instead of holding the
            # inbox hostage in PROBE forever.
            self._empty_votes.add(peer)
            if len(self._empty_votes) >= self.f + 1:
                if rec is not None:
                    rec.count("st.noop")
                held = self._held
                self._to_idle()
                for p, m in held:
                    self.node._inbox.put_nowait((p, m))
            return
        if (
            not _epoch_ok(meta.from_epoch)
            or not _epoch_ok(meta.upto_epoch)
            or not isinstance(meta.digest, bytes)
            or len(meta.digest) != 32
            or not _int_ok(meta.size)
            or not _int_ok(meta.chunks)
            or meta.size > _tcp._ST_MAX_BYTES
            or not (1 <= meta.chunks <= _tcp._ST_MAX_CHUNKS)
        ):
            self._attribute(peer, "bad-meta")
            return
        if meta.from_epoch != self._from or meta.upto_epoch < self._from:
            if rec is not None:
                rec.count("st.unexpected")
            return
        if self._pinned and meta.upto_epoch != self._target:
            return  # stale probe reply after the range was pinned
        self._offers[peer] = (
            meta.upto_epoch, meta.digest, meta.size, meta.chunks
        )
        self._advance_probe()

    def _advance_probe(self) -> None:
        # quorum: f+1 peers offering the identical (upto, digest, size,
        # chunks) tuple — pick the highest-epoch such tuple
        by_tuple: Dict[Tuple[int, bytes, int, int], List[str]] = {}
        for p, offer in self._offers.items():
            by_tuple.setdefault(offer, []).append(p)
        agreed = [
            (offer, peers)
            for offer, peers in by_tuple.items()
            if len(peers) >= self.f + 1
        ]
        if agreed:
            offer, peers = max(agreed, key=lambda op: op[0][0])
            self._target = offer[0]
            self._expect = (offer[1], offer[2], offer[3])
            self._quorum_peers = sorted(peers)
            self._fetch_from_next()
            return
        # no agreement yet.  Peers at different epochs legitimately
        # offer different ranges; once ≥ 2f+1 replied (≥ f+1 honest),
        # pin the (f+1)-th highest offered upto — at least one honest
        # peer can serve it — and re-request that exact range.
        if self._pinned or len(self._offers) < max(2 * self.f + 1, 1):
            return
        tops = sorted((u for u, _, _, _ in self._offers.values()), reverse=True)
        if len(tops) <= self.f:
            return
        target = tops[self.f]
        if target < self._from:
            return
        self._pinned = True
        self._target = target
        pin_peers = [
            p for p, (u, _, _, _) in self._offers.items() if u >= target
        ]
        self._offers.clear()
        for p in pin_peers:
            self.node.send_control(p, SnapReq(self._from, target, False))

    def _fetch_from_next(self) -> None:
        for p in self._quorum_peers:
            if p not in self._failed:
                self._provider = p
                self._reset_fetch()
                self.state = self.FETCH
                self.node.send_control(
                    p, SnapReq(self._from, self._target, True)
                )
                return
        self._restart_or_abort("providers-exhausted")

    def _restart_or_abort(self, reason: str) -> None:
        rec = _obs.ACTIVE
        self._restarts += 1
        if self._restarts < _MAX_RESTARTS:
            if rec is not None:
                rec.count("st.retry")
            self._begin_probe()
            return
        # give up: flush the parked frames so the node is no worse off
        # than the legacy severed-link behaviour; the next gap retries
        if rec is not None:
            rec.count("st.aborted")
            rec.event("st_reject", peer=self._provider or "-", reason=reason)
        held = self._held
        self._to_idle()
        for p, m in held:
            self.node._inbox.put_nowait((p, m))

    async def _provider_failed(self, reason: str) -> None:
        """The chosen provider served garbage: attribute, exclude,
        retry against the next quorum peer."""
        rec = _obs.ACTIVE
        if rec is not None:
            rec.count("st.forged")
            rec.event(
                "st_reject",
                peer=self._provider or "-",
                reason=reason,
                epoch=self._target,
            )
        self._attribute(self._provider, reason, kind=FaultKind.INVALID_SNAPSHOT)
        if self._provider is not None:
            self._failed.add(self._provider)
        self._provider = None
        self._fetch_from_next()

    async def _on_chunk(self, peer: str, msg: SnapChunk) -> None:
        if self.state != self.FETCH or peer != self._provider:
            rec = _obs.ACTIVE
            if rec is not None:
                rec.count("st.unexpected")
            return
        digest, size, chunks = self._expect
        data = msg.data
        cb = _tcp._ST_CHUNK_BYTES
        if (
            not _int_ok(msg.index)
            or not _int_ok(msg.offset)
            or not isinstance(data, (bytes, bytearray))
            or msg.index != self._next_idx
            or msg.index >= chunks
            or msg.offset != msg.index * cb
            or len(data) > cb
            or msg.offset + len(data) > size
            or (msg.index < chunks - 1 and len(data) != cb)
            or (msg.index == chunks - 1 and msg.offset + len(data) != size)
        ):
            await self._provider_failed("bad-chunk")
            return
        self._parts.append(bytes(data))
        self._got += len(data)
        self._next_idx += 1

    async def _on_done(self, peer: str, msg: SnapDone) -> None:
        if self.state != self.FETCH or peer != self._provider:
            rec = _obs.ACTIVE
            if rec is not None:
                rec.count("st.unexpected")
            return
        digest, size, chunks = self._expect
        if self._next_idx != chunks or self._got != size:
            await self._provider_failed("short-stream")
            return
        payload = b"".join(self._parts)
        if msg.digest != digest or snapshot_digest(payload) != digest:
            await self._provider_failed("forged-digest")
            return
        try:
            batches = loads(payload)
        except SerializationError:
            await self._provider_failed("undecodable")
            return
        # structural belt-and-braces (an honest payload always passes):
        # exactly one batch per epoch, contiguous over the pinned range
        ok = isinstance(batches, list) and len(batches) == (
            self._target - self._from + 1
        )
        if ok:
            for e, b in zip(range(self._from, self._target + 1), batches):
                if getattr(b, "epoch", None) != e:
                    ok = False
                    break
        if not ok:
            await self._provider_failed("bad-shape")
            return
        await self._install(batches, len(payload), chunks)

    async def _install(
        self, batches: List[Any], nbytes: int, chunks: int
    ) -> None:
        # Renumber per-link recv expectations BEFORE the install
        # checkpoint: everything below the first parked frame is either
        # applied or covered by the snapshot, so the checkpoint may
        # claim it — and the parked frames' WAL records then count
        # contiguously on top of this base after a crash.
        for p, first in self._held_first.items():
            if first > self.node._applied_seq.get(p, 0):
                self.node._applied_seq[p] = first - 1
        # The install writes a durable checkpoint (WAL append + fsync +
        # possible compaction) — run it on an executor thread so the
        # loop keeps serving, under the algorithm lock so it serializes
        # with the pump.  Routing the produced step and re-injecting
        # the parked frames stay inside the lock: the pump must not see
        # the parked frames before the step's messages are numbered.
        loop = asyncio.get_event_loop()
        async with self._lock:
            if self._install_fn is not None:
                step = await loop.run_in_executor(
                    None, self._install_fn, self._target, batches
                )
            else:
                step = await loop.run_in_executor(
                    None, self.node.algo.install_snapshot, self._target, batches
                )
            self.installed += 1
            rec = _obs.ACTIVE
            if rec is not None:
                rec.count("st.installed")
                rec.event(
                    "st_transfer",
                    peer=self._provider or "-",
                    from_epoch=self._from,
                    upto_epoch=self._target,
                    bytes=nbytes,
                    chunks=chunks,
                    retries=self._restarts + len(self._failed),
                )
            held = self._held
            self._to_idle()
            if step is not None:
                await self.node._route(step)
            for p, m in held:
                self.node._inbox.put_nowait((p, m))

    def _to_idle(self) -> None:
        self.state = self.IDLE
        self._offers.clear()
        self._empty_votes.clear()
        self._failed.clear()
        self._provider = None
        self._expect = None
        self._target = None
        self._pinned = False
        self._reset_fetch()
        self._held = []
        self._held_first = {}

    def _attribute(
        self, peer: Optional[str], reason: str,
        kind: FaultKind = FaultKind.INVALID_MESSAGE,
    ) -> None:
        if peer is None:
            return
        # FaultLog.init routes through the shared debug-log + obs path
        self.node.faults.extend(FaultLog.init(peer, kind))


def attach_transfer(
    node: Any,
    num_faulty: Optional[int] = None,
    retain: int = 1024,
    install_fn: Optional[Callable[[int, List[Any]], Any]] = None,
) -> CatchupManager:
    """Wire a :class:`CatchupManager` onto a ``TcpNode``: sets
    ``node.transfer`` and chains the output hook so every committed
    batch lands in the provider-side :class:`SnapshotStore`."""
    f = node.netinfo.num_faulty if num_faulty is None else int(num_faulty)
    mgr = CatchupManager(
        node, f, store=SnapshotStore(retain), install_fn=install_fn
    )
    node.transfer = mgr
    prev = node.on_output

    def _watch(out: Any, _prev=prev, _mgr=mgr) -> None:
        _mgr.store.record(out)
        if _prev is not None:
            _prev(out)

    node.on_output = _watch
    return mgr
