"""Durable algorithm wrapper + crash recovery.

``DurableAlgo`` wraps any sans-IO algorithm (``HoneyBadger``,
``QueueingHoneyBadger``, ``Broadcast``, …) and write-ahead-logs every
inbound event — ``handle_input`` / ``handle_message`` append to the WAL
*before* the event is applied, so a crash at any instant leaves the log
at-or-ahead of the applied state, never behind.  Recovery loads the
last ``CHECKPOINT`` snapshot and replays the records after it; the
determinism guarantee (badgerlint ``determinism`` rule over
``protocols/`` + ``core/``) makes the replayed state — and the replayed
outbound ``Step`` stream — byte-identical to the pre-crash run.

Checkpoint cadence is epoch-granular: by default a snapshot is written
after every ``checkpoint_every``-th protocol output (one output = one
committed epoch for the honey-badger family).  Two snapshot modes:

- **inline** (``auto_checkpoint=True``, default): the snapshot is
  appended inside ``handle_*`` right after the event applies.  Right
  for the in-process ``TestNetwork`` plane, where a restarted node's
  replayed steps are discarded (their messages were already delivered
  by the in-memory dispatcher).
- **quiescent** (``auto_checkpoint=False``): the *driver* calls
  :meth:`maybe_checkpoint` between pump iterations, after the step's
  messages have been routed, so the ``meta_fn`` snapshot of transport
  state (per-peer send sequence numbers) is consistent with the
  algorithm snapshot.  Required for real-TCP recovery, where replayed
  outbound frames must be renumbered continuously with the pre-crash
  stream.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from ..harness import checkpoint
from . import wal as _wal


class RecoveryError(Exception):
    pass


class DurableAlgo:
    """Write-ahead wrapper: logs every inbound event, snapshots at
    epoch boundaries, and otherwise behaves exactly like the wrapped
    algorithm (attribute access is delegated)."""

    def __init__(
        self,
        algo: Any,
        wal: _wal.WalWriter,
        checkpoint_every: int = 1,
        auto_checkpoint: bool = True,
        meta_fn: Optional[Callable[[], Dict[str, Any]]] = None,
        bootstrap: bool = True,
    ):
        self.algo = algo
        self.wal = wal
        self.checkpoint_every = max(1, checkpoint_every)
        self.auto_checkpoint = auto_checkpoint
        self.meta_fn = meta_fn
        self._outputs_since_ckpt = 0
        if bootstrap:
            # A fresh log must start with a snapshot: recovery replays
            # *from* a CHECKPOINT record, and the initial state is one.
            self.checkpoint()

    # -- the sans-IO surface, write-ahead ------------------------------

    def handle_input(self, value: Any) -> Any:
        self.wal.append_input(value)
        step = self.algo.handle_input(value)
        self._note(step)
        return step

    def handle_message(self, sender: Any, message: Any) -> Any:
        self.wal.append_message(sender, message)
        step = self.algo.handle_message(sender, message)
        self._note(step)
        return step

    def _note(self, step: Any) -> None:
        self._outputs_since_ckpt += len(step.output)
        if self.auto_checkpoint:
            self.maybe_checkpoint()

    # -- snapshots ------------------------------------------------------

    def maybe_checkpoint(self) -> bool:
        if self._outputs_since_ckpt >= self.checkpoint_every:
            self.checkpoint()
            return True
        return False

    def checkpoint(self) -> None:
        meta = dict(self.meta_fn()) if self.meta_fn is not None else {}
        self.wal.append_checkpoint(checkpoint.save(self.algo), meta)
        self._outputs_since_ckpt = 0

    def install_snapshot(self, upto_epoch: int, batches: List[Any]) -> Any:
        """State transfer: fast-forward the wrapped algorithm through a
        quorum-verified batch range and pin the jump with a fresh
        CHECKPOINT record, so a crash after install recovers *from* the
        transferred state, never from the pre-gap log.

        Returns the wrapped algorithm's fast-forward ``Step`` (the
        skipped epochs surface as outputs).  Raises
        :class:`RecoveryError` when the wrapped algorithm has no
        ``fast_forward`` (the DynamicHoneyBadger family needs the
        join-plan path instead)."""
        ff = getattr(self.algo, "fast_forward", None)
        if ff is None:
            raise RecoveryError(
                f"{type(self.algo).__name__} cannot install a snapshot "
                "(no fast_forward)"
            )
        step = ff(upto_epoch, batches)
        self.checkpoint()
        return step

    # -- delegation ------------------------------------------------------

    def terminated(self) -> bool:
        return self.algo.terminated()

    def __getattr__(self, name: str) -> Any:
        return getattr(self.algo, name)


class Recovery:
    """Result of :func:`recover` — the restored algorithm plus
    everything the transport needs to rejoin.

    - ``algo``: the wrapped algorithm, caught up through the last
      logged event.
    - ``steps``: the ``Step`` objects regenerated by replay (records
      after the last snapshot).  The real-TCP driver routes these so
      the outbound replay buffer holds the frames a peer may have
      missed; the in-process plane discards them (already delivered).
    - ``meta``: the last snapshot's driver metadata (send seqs).
    - ``recv_seqs``: the per-link receive sequence numbers the resume
      handshake reports.  When the last snapshot's meta carries a
      ``"recv_seqs"`` base (written by the real-TCP driver, and
      rewritten by state-transfer installs), the count is that base
      plus the MESSAGE records *after* the snapshot; legacy logs
      without the key fall back to counting the whole log.  Both agree
      on gap-free logs — the base exists so a state-transfer jump
      (which skips wire seqs the node never saw) stays accurate.
    - ``clean``: False when the log ended in a torn tail (expected
      after a crash; the tail event was never applied pre-crash
      either, so replay is still exact).
    """

    def __init__(
        self,
        algo: Any,
        steps: List[Any],
        meta: Dict[str, Any],
        recv_seqs: Dict[Any, int],
        clean: bool,
    ):
        self.algo = algo
        self.steps = steps
        self.meta = meta
        self.recv_seqs = recv_seqs
        self.clean = clean

    def resume(
        self,
        wal: _wal.WalWriter,
        checkpoint_every: int = 1,
        auto_checkpoint: bool = True,
        meta_fn: Optional[Callable[[], Dict[str, Any]]] = None,
    ) -> DurableAlgo:
        """Re-wrap the recovered algorithm around a writer appending to
        the same log (no bootstrap snapshot — the log already has
        one)."""
        return DurableAlgo(
            self.algo,
            wal,
            checkpoint_every=checkpoint_every,
            auto_checkpoint=auto_checkpoint,
            meta_fn=meta_fn,
            bootstrap=False,
        )


def recover(path: str, ops: Any = None) -> Recovery:
    """Restore a node from its WAL: last snapshot + deterministic
    replay of everything after it."""
    records, clean = _wal.read_records(path)
    last_idx = -1
    for i, r in enumerate(records):
        if r.kind == _wal.CHECKPOINT:
            last_idx = i
    if last_idx < 0:
        raise RecoveryError(f"no checkpoint record in WAL {path!r}")
    state_bytes, meta = _wal.decode_checkpoint(records[last_idx].payload)
    algo = checkpoint.load(state_bytes, ops=ops)
    steps: List[Any] = []
    base = meta.get("recv_seqs")
    meta_based = isinstance(base, dict)
    recv_seqs: Dict[Any, int] = dict(base) if meta_based else {}
    for i, r in enumerate(records):
        if r.kind == _wal.MESSAGE:
            sender, message = _wal.decode_message(r.payload)
            if i > last_idx:
                recv_seqs[sender] = recv_seqs.get(sender, 0) + 1
                steps.append(algo.handle_message(sender, message))
            elif not meta_based:
                recv_seqs[sender] = recv_seqs.get(sender, 0) + 1
        elif r.kind == _wal.INPUT and i > last_idx:
            steps.append(algo.handle_input(_wal.decode_input(r.payload)))
    return Recovery(algo, steps, meta, recv_seqs, clean)
