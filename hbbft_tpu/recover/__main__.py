"""Recovery-plane CLI — offline WAL compaction + bounded-memory bench.

``python -m hbbft_tpu.recover --compact <wal>``
    Drop every record preceding the last checkpoint, atomically
    (``wal.compact_wal``).  Replay of the compacted log reaches a state
    structurally equal to full-log replay — pinned by
    ``tests/test_recover.py``.

``python -m hbbft_tpu.recover --gc-bench --epochs 500 --gc on|off``
    Long-run memory probe: drive a ``GatewayCore`` exactly-once ledger
    (the dominant per-epoch accumulator of a serving validator) for N
    epochs of synthetic committed traffic and sample RSS.  With GC on
    the acked ledger and RSS stay flat; with it off both grow linearly
    — the numbers quoted in ROADMAP come from running this twice.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from .wal import compact_wal


def _rss_kb() -> int:
    """VmRSS in kB from /proc/self/status (Linux; 0 elsewhere)."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1])
    except OSError:
        pass
    return 0


def _gc_bench(epochs: int, gc_on: bool, txs_per_epoch: int = 200) -> int:
    from ..serve.gateway import GatewayCore
    from ..serve.protocol import PROTO_VERSION, ClientHello, SubmitTx

    core = GatewayCore()
    conn = "bench-conn"
    _replies, drop = core.on_hello(
        conn, ClientHello(PROTO_VERSION, "tenant-0", "client-0")
    )
    if drop:
        print("gc-bench: hello rejected", file=sys.stderr)
        return 1
    seq = 0
    rss_samples: List[int] = []
    for epoch in range(epochs):
        for _ in range(txs_per_epoch):
            seq += 1
            core.on_submit(conn, SubmitTx(seq, b"x" * 64), now=float(epoch))
        for tx in core.drain(txs_per_epoch):
            core.on_committed(tx, epoch, float(epoch))
        if gc_on:
            core.gc_epochs(epoch)
        if epoch % 50 == 0 or epoch == epochs - 1:
            rss_samples.append(_rss_kb())
            print(
                f"epoch {epoch:5d}  acked={len(core.acked):8d}  "
                f"pending={len(core.pending):6d}  rss={rss_samples[-1]} kB"
            )
    grew = rss_samples[-1] - rss_samples[0]
    print(
        f"gc={'on' if gc_on else 'off'}: {epochs} epochs x "
        f"{txs_per_epoch} txs, final acked ledger {len(core.acked)} "
        f"entries, RSS {rss_samples[0]} -> {rss_samples[-1]} kB "
        f"({grew:+d} kB)"
    )
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="python -m hbbft_tpu.recover")
    ap.add_argument(
        "--compact",
        metavar="WAL",
        help="compact a WAL in place: drop records before the last checkpoint",
    )
    ap.add_argument(
        "--gc-bench",
        action="store_true",
        help="bounded-memory probe: gateway ledger growth with/without epoch GC",
    )
    ap.add_argument("--epochs", type=int, default=500)
    ap.add_argument("--gc", choices=("on", "off"), default="on")
    args = ap.parse_args(argv)
    if args.compact:
        if not os.path.exists(args.compact):
            print(f"no such WAL: {args.compact}", file=sys.stderr)
            return 1
        dropped, reclaimed = compact_wal(args.compact)
        print(
            f"compacted {args.compact}: dropped {dropped} records, "
            f"reclaimed {reclaimed} bytes"
        )
        return 0
    if args.gc_bench:
        return _gc_bench(max(1, args.epochs), args.gc == "on")
    ap.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
