"""Restart driver — wiring WAL + checkpoint + TCP resume together.

Two entry points mirror a validator's lifecycle:

- :func:`durable_tcp_node` builds a *fresh* node whose algorithm is
  write-ahead logged, with epoch-granular snapshots taken at the
  quiescent point between pump iterations (``TcpNode.on_step``), so
  each ``CHECKPOINT`` record's meta carries transport send-sequence
  numbers consistent with the algorithm state.
- :func:`restart_tcp_node` SIGKILL-recovery: load the last snapshot,
  deterministically replay the WAL tail, and hand back a node whose
  per-link sequence numbers continue the pre-crash stream exactly —
  outbound via the snapshot's ``send_seqs`` meta, inbound via the
  per-sender count of logged messages.  :func:`prime_replay` routes
  the regenerated steps into the transport, so the replay buffer holds
  (renumbered-identically) every frame a peer may have missed; peers'
  inbound dedup drops the ones they already consumed.  Run it before
  ``start()`` so the resume handshake sees the full buffer.

The exactly-once argument, end to end: an inbound frame is WAL-logged
*before* it is applied, so the ``ResumeHello`` high-water mark (count
of logged messages) never claims an unapplied frame — peers re-send
anything newer, and dedup-by-seq drops anything older.  Outbound,
deterministic replay regenerates byte-identical frames with identical
sequence numbers, so the receiving side's dedup is exact even if the
crash raced the original send.
"""

from __future__ import annotations

import asyncio
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..core.network_info import NetworkInfo
from ..transport.tcp import TcpNode
from .node import DurableAlgo, Recovery, recover
from .transfer import attach_transfer
from .wal import WalWriter


def _meta_fn(node_ref: Dict[str, TcpNode]) -> Callable[[], Dict[str, Any]]:
    def fn() -> Dict[str, Any]:
        node = node_ref.get("node")
        if node is None:
            return {"send_seqs": {}, "recv_seqs": {}}
        # recv base = applied (WAL-logged) wire-seq high-water per link,
        # NOT the logged-message count: a state-transfer install skips
        # wire seqs this node never saw, and the resume handshake must
        # claim them so peers don't re-send evicted history.
        return {"send_seqs": node.send_seqs, "recv_seqs": node.applied_seqs}

    return fn


def _on_step(
    on_checkpoint: Optional[Callable[[TcpNode], None]],
) -> Callable[[TcpNode], None]:
    """Quiescent-point hook: checkpoint when due, then GC per-epoch
    state the snapshot now covers (bounded-memory long runs)."""

    def hook(n: TcpNode) -> None:
        if n.algo.maybe_checkpoint():
            gc = getattr(n.algo, "gc_epochs", None)
            if gc is not None:
                gc()
            if on_checkpoint is not None:
                on_checkpoint(n)

    return hook


def durable_tcp_node(
    our_addr: str,
    peer_addrs: List[str],
    new_algo: Callable[[NetworkInfo], Any],
    wal_path: str,
    checkpoint_every: int = 1,
    netinfo: Optional[NetworkInfo] = None,
    fsync: str = "interval",
    transfer: bool = False,
    snapshot_retain: int = 1024,
    on_checkpoint: Optional[Callable[[TcpNode], None]] = None,
    **kw: Any,
) -> TcpNode:
    """A fresh TCP node with a durable, write-ahead-logged algorithm.
    ``transfer=True`` attaches the state-transfer manager: the node
    serves snapshots to dark peers and escalates its own replay gaps
    into a catch-up instead of a severed link."""
    node_ref: Dict[str, TcpNode] = {}

    def build(ni: NetworkInfo) -> DurableAlgo:
        return DurableAlgo(
            new_algo(ni),
            WalWriter(wal_path, fsync=fsync),
            checkpoint_every=checkpoint_every,
            auto_checkpoint=False,
            meta_fn=_meta_fn(node_ref),
        )

    node = TcpNode(our_addr, peer_addrs, build, netinfo=netinfo, **kw)
    node_ref["node"] = node
    node.on_step = _on_step(on_checkpoint)
    if transfer:
        attach_transfer(node, retain=snapshot_retain)
    return node


def restart_tcp_node(
    our_addr: str,
    peer_addrs: List[str],
    wal_path: str,
    ops: Any = None,
    checkpoint_every: int = 1,
    netinfo: Optional[NetworkInfo] = None,
    fsync: str = "interval",
    transfer: bool = False,
    snapshot_retain: int = 1024,
    on_checkpoint: Optional[Callable[[TcpNode], None]] = None,
    **kw: Any,
) -> Tuple[TcpNode, Recovery]:
    """Restore a crashed node from its WAL.  Call
    :func:`prime_replay` with the returned recovery's steps, then
    ``await node.start()``.  With ``transfer=True`` a node that was
    dark past its peers' replay bound catches up via state transfer
    instead of staying severed."""
    recovery = recover(wal_path, ops=ops)
    node_ref: Dict[str, TcpNode] = {}

    def build(ni: NetworkInfo) -> DurableAlgo:
        return recovery.resume(
            WalWriter(wal_path, fsync=fsync),
            checkpoint_every=checkpoint_every,
            auto_checkpoint=False,
            meta_fn=_meta_fn(node_ref),
        )

    node = TcpNode(
        our_addr,
        peer_addrs,
        build,
        netinfo=netinfo,
        resume_recv=dict(recovery.recv_seqs),
        resume_send=dict(recovery.meta.get("send_seqs", {})),
        **kw,
    )
    node_ref["node"] = node
    node.on_step = _on_step(on_checkpoint)
    if transfer:
        attach_transfer(node, retain=snapshot_retain)
    return node, recovery


async def prime_replay(node: TcpNode, steps: List[Any]) -> None:
    """Route the recovery's regenerated steps through the transport:
    outbound frames renumber identically to the pre-crash stream and
    land in the replay buffer (no link is up yet), ready for the
    resume handshakes to trim + re-send."""
    for i, step in enumerate(steps):
        await node._route(step)
        # With no link up, _route never actually awaits — a long WAL
        # tail would monopolize the loop for its whole replay.  Yield
        # periodically so concurrent servers (metrics, peers already
        # running in this process) keep breathing.
        if i % 64 == 63:
            await asyncio.sleep(0)
