"""Durable write-ahead log — CRC-framed records + epoch snapshots.

One append-only file per node holds everything a crashed validator
needs to come back: full ``checkpoint.save`` snapshots (``CHECKPOINT``
records, written at epoch granularity) interleaved with the inbound
event stream between snapshots (``INPUT`` / ``MESSAGE`` records, one
per ``handle_input`` / ``handle_message`` call, written *before* the
event is applied).  Because every algorithm is a deterministic sans-IO
state machine (the ``determinism`` lint rule guarantees it), replaying
the records after the last snapshot regenerates the exact pre-crash
state *and* the exact outbound ``Step`` stream — which is what lets
the transport's session resumption renumber and re-send only the
frames a peer never received.

File format::

    magic   := b"HBWAL001"                       (8 bytes, file start)
    record  := kind(1) || len(4, BE) || crc32(4, BE) || payload(len)

A crash mid-append leaves a truncated or CRC-failing *tail*;
:func:`read_records` stops cleanly at the first bad record and reports
``clean=False`` — everything before the tail is intact by CRC.

Payload encoding is pickle protocol 5, the same trust model as
``harness/checkpoint.py``: the WAL is trusted local state, never
loaded from an untrusted source (the *wire* codec remains
``core/serialize.py``).  ``MESSAGE`` payloads are ``(sender, message)``
pairs; ``CHECKPOINT`` payloads are ``(state_bytes, meta)`` where
``state_bytes`` is ``checkpoint.save`` output and ``meta`` is a small
dict the restart driver uses for transport continuity (per-peer send
sequence numbers at snapshot time).

Durability knobs: every append is written + flushed to the OS
immediately; ``fsync`` batching is delegated to a background syncer
thread (``hbbft-wal-sync``) so the protocol pump never blocks on disk,
with ``fsync="always"`` available for tests and paranoid deployments.

**Compaction** (state-transfer PR): recovery only ever reads the last
``CHECKPOINT`` and the records after it, so everything before that
snapshot is dead weight — an indefinitely-running node would grow its
log without bound.  :func:`compact_records` drops the dead prefix
(injecting the counted per-sender receive seqs into the surviving
snapshot's meta so ``recover()`` stays exact without the dropped
``MESSAGE`` records); :func:`compact_wal` applies it to a closed log
atomically (temp file + ``os.replace``); ``WalWriter.compact`` does
the same on a live writer, and ``append_checkpoint`` triggers it
automatically once the log passes a size or record-count threshold.
The ``HBBFT_TPU_WAL_COMPACT`` env knob sets the byte threshold
(default 4 MiB) or disables the trigger (``off``/``0``/``no``).
"""

from __future__ import annotations

import dataclasses
import os
import pickle
import threading
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..obs import recorder as _obs

_MAGIC = b"HBWAL001"
_HDR = 1 + 4 + 4  # kind + length + crc32
_PROTOCOL = 5

# Automatic compaction: fire at append_checkpoint once the log passes
# either bound.  The byte threshold is tunable via HBBFT_TPU_WAL_COMPACT
# ("off"/"0"/"no"/"false" disables; an integer sets the byte threshold).
_COMPACT_ENV = "HBBFT_TPU_WAL_COMPACT"
_COMPACT_DEFAULT_BYTES = 4 * 1024 * 1024
_COMPACT_MIN_RECORDS = 4096


def _compact_threshold() -> Optional[int]:
    """The live byte threshold, or ``None`` when compaction is off."""
    raw = os.environ.get(_COMPACT_ENV, "").strip().lower()
    if raw in ("off", "0", "no", "false"):
        return None
    if raw:
        try:
            return max(1, int(raw))
        except ValueError:
            pass
    return _COMPACT_DEFAULT_BYTES

CHECKPOINT = 1
INPUT = 2
MESSAGE = 3
_KINDS = (CHECKPOINT, INPUT, MESSAGE)

# Racecheck hook (analysis/racecheck.py): when the runtime lockset
# checker is installed it replaces this with a callable that wraps each
# new writer's lock in a tracked view, so the append path vs the
# background syncer thread is race-checked.
_TRACK_WAL: Optional[Callable[["WalWriter"], None]] = None


class WalError(Exception):
    pass


@dataclasses.dataclass(frozen=True)
class Record:
    kind: int
    payload: bytes


def _frame_record(kind: int, payload: bytes) -> bytes:
    return (
        bytes([kind])
        + len(payload).to_bytes(4, "big")
        + (zlib.crc32(payload) & 0xFFFFFFFF).to_bytes(4, "big")
        + payload
    )


def read_records(path: str) -> Tuple[List[Record], bool]:
    """Scan a WAL file → ``(records, clean)``.

    ``clean`` is False when the file ends in a truncated or
    CRC-failing tail (the signature of a crash mid-append); the
    records before the tail are returned and are CRC-intact.
    """
    try:
        with open(path, "rb") as f:
            data = f.read()
    except FileNotFoundError:
        return [], True
    if not data.startswith(_MAGIC):
        return [], len(data) == 0
    pos = len(_MAGIC)
    records: List[Record] = []
    while pos < len(data):
        if pos + _HDR > len(data):
            return records, False  # truncated header
        kind = data[pos]
        length = int.from_bytes(data[pos + 1 : pos + 5], "big")
        crc = int.from_bytes(data[pos + 5 : pos + 9], "big")
        end = pos + _HDR + length
        if kind not in _KINDS or end > len(data):
            return records, False  # unknown kind / truncated payload
        payload = data[pos + _HDR : end]
        if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
            return records, False  # torn write
        records.append(Record(kind, payload))
        pos = end
    return records, True


def decode_checkpoint(payload: bytes) -> Tuple[bytes, Dict[str, Any]]:
    state_bytes, meta = pickle.loads(payload)
    return state_bytes, meta


def decode_input(payload: bytes) -> Any:
    return pickle.loads(payload)


def decode_message(payload: bytes) -> Tuple[Any, Any]:
    sender, message = pickle.loads(payload)
    return sender, message


# -- compaction --------------------------------------------------------


def compact_records(records: List[Record]) -> Tuple[List[Record], int]:
    """Drop every record preceding the last ``CHECKPOINT`` →
    ``(compacted_records, dropped_count)``.

    Recovery never reads the dropped prefix — except for the per-sender
    ``MESSAGE`` counts that seed the resume handshake's receive seqs.
    When the surviving snapshot's meta lacks a ``"recv_seqs"`` base
    (legacy logs), the counts over the dropped-and-kept prefix are
    injected into it, so meta-based accounting in ``recover()`` is
    exact on the compacted log."""
    last_idx = -1
    for i, r in enumerate(records):
        if r.kind == CHECKPOINT:
            last_idx = i
    if last_idx <= 0:
        return list(records), 0  # nothing before the snapshot (or none)
    ckpt = records[last_idx]
    state_bytes, meta = decode_checkpoint(ckpt.payload)
    if not isinstance(meta.get("recv_seqs"), dict):
        counts: Dict[Any, int] = {}
        for r in records[:last_idx]:
            if r.kind == MESSAGE:
                sender, _ = decode_message(r.payload)
                counts[sender] = counts.get(sender, 0) + 1
        meta = dict(meta)
        meta["recv_seqs"] = counts
        ckpt = Record(
            CHECKPOINT,
            pickle.dumps((state_bytes, meta), protocol=_PROTOCOL),
        )
    return [ckpt] + list(records[last_idx + 1 :]), last_idx


def _write_wal(path: str, records: List[Record]) -> int:
    """Atomically replace ``path`` with a log holding ``records``;
    returns the new file size."""
    tmp = path + ".compact.tmp"
    with open(tmp, "wb") as f:
        f.write(_MAGIC)
        for r in records:
            f.write(_frame_record(r.kind, r.payload))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return os.path.getsize(path)


def compact_wal(path: str) -> Tuple[int, int]:
    """Offline compaction of a closed WAL →
    ``(dropped_records, reclaimed_bytes)``.  A torn tail is preserved
    as-is would be wrong — it is already unreadable — so the rewritten
    log simply ends at the last intact record."""
    before = os.path.getsize(path)
    records, _clean = read_records(path)
    compacted, dropped = compact_records(records)
    if dropped == 0:
        return 0, 0
    after = _write_wal(path, compacted)
    return dropped, before - after


class WalWriter:
    """Append-only writer with background fsync batching.

    Thread-shape: the protocol pump appends (event-loop thread); the
    ``hbbft-wal-sync`` daemon fsyncs on an interval.  ``_lock`` guards
    the file handle and the dirty counter — the only state both
    threads touch."""

    def __init__(
        self,
        path: str,
        fsync: str = "interval",  # "always" | "interval" | "off"
        fsync_interval_s: float = 0.05,
    ):
        if fsync not in ("always", "interval", "off"):
            raise ValueError(f"bad fsync policy: {fsync!r}")
        self.path = path
        self._fsync = fsync
        self._interval = fsync_interval_s
        fresh = not os.path.exists(path) or os.path.getsize(path) == 0
        self._f = open(path, "ab")
        self._lock = threading.Lock()
        self._dirty = 0
        self._size = 0 if fresh else os.path.getsize(path)
        self._records = 0  # appends since open (size covers resumed logs)
        self._closed = False
        self._wake = threading.Event()
        self._syncer: Optional[threading.Thread] = None
        if fresh:
            self._f.write(_MAGIC)
            self._f.flush()
        if _TRACK_WAL is not None:
            _TRACK_WAL(self)
        if fsync == "interval":
            self._syncer = threading.Thread(
                target=self._sync_loop, name="hbbft-wal-sync", daemon=True
            )
            self._syncer.start()

    # -- append paths --------------------------------------------------

    def append(self, kind: int, payload: bytes) -> None:
        if kind not in _KINDS:
            raise WalError(f"bad record kind: {kind}")
        rec = _frame_record(kind, payload)
        with self._lock:
            if self._closed:
                raise WalError("append to closed WAL")
            self._f.write(rec)
            self._f.flush()
            self._size += len(rec)
            self._records += 1
            records = self._records
            if self._fsync == "always":
                os.fsync(self._f.fileno())
            else:
                self._dirty += 1
        # emitted AFTER releasing _lock (the compact() pattern): the
        # recorder takes its own lock and may mirror into a flight
        # ring.  ``records`` is the log's high-water mark — what the
        # flight-recorder crash test joins against the on-disk WAL.
        obs_rec = _obs.ACTIVE
        if obs_rec is not None:
            obs_rec.event(
                "wal_append", records=records, kind=kind, path=self.path
            )

    def append_checkpoint(
        self, state_bytes: bytes, meta: Optional[Dict[str, Any]] = None
    ) -> None:
        self.append(
            CHECKPOINT,
            pickle.dumps((state_bytes, dict(meta or {})), protocol=_PROTOCOL),
        )
        self.maybe_compact()

    def append_input(self, value: Any) -> None:
        self.append(INPUT, pickle.dumps(value, protocol=_PROTOCOL))

    def append_message(self, sender: Any, message: Any) -> None:
        self.append(MESSAGE, pickle.dumps((sender, message), protocol=_PROTOCOL))

    # -- compaction ----------------------------------------------------

    def maybe_compact(self) -> bool:
        """Compact when the log passed the size or record-count
        threshold (called after every checkpoint append)."""
        threshold = _compact_threshold()
        if threshold is None:
            return False
        with self._lock:
            due = (
                self._size >= threshold
                or self._records >= _COMPACT_MIN_RECORDS
            )
        if not due:
            return False
        return self.compact() > 0

    def compact(self) -> int:
        """Drop all records before the last checkpoint, atomically, on
        the live log → dropped record count.  Safe against the syncer
        thread: the rewrite happens under ``_lock`` and the handle is
        reopened on the replacement file before the lock is released."""
        with self._lock:
            if self._closed:
                raise WalError("compact of closed WAL")
            self._f.flush()
            if self._dirty:
                os.fsync(self._f.fileno())
                self._dirty = 0
            before = os.path.getsize(self.path)
            records, _clean = read_records(self.path)
            compacted, dropped = compact_records(records)
            if dropped == 0:
                return 0
            self._f.close()
            after = _write_wal(self.path, compacted)
            self._f = open(self.path, "ab")
            self._size = after
            self._records = len(compacted)
        rec = _obs.ACTIVE
        if rec is not None:
            rec.count("wal.compacted")
            rec.event(
                "wal_compact",
                dropped=dropped,
                kept=len(compacted),
                bytes=before - after,
            )
        return dropped

    # -- durability ----------------------------------------------------

    def sync(self) -> None:
        """Force an fsync now (no-op when nothing is dirty)."""
        with self._lock:
            if self._dirty and not self._f.closed:
                os.fsync(self._f.fileno())
                self._dirty = 0

    def _sync_loop(self) -> None:
        while True:
            self._wake.wait(self._interval)
            if self._wake.is_set():
                return  # close() requested shutdown
            self.sync()

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._wake.set()
        if self._syncer is not None:
            self._syncer.join(timeout=5.0)
        with self._lock:
            if self._dirty and not self._f.closed:
                os.fsync(self._f.fileno())
                self._dirty = 0
            self._f.close()

    def __enter__(self) -> "WalWriter":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()
