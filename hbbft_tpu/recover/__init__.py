"""Crash recovery — durable WAL, checkpoint restore, restart drivers.

The recovery stack in one sentence: every inbound event is CRC-framed
into an append-only write-ahead log *before* it is applied
(``wal.py``), epoch-granular ``checkpoint.save`` snapshots bound the
replay tail (``node.py``), and the restart drivers (``driver.py``)
rebuild a killed node whose transport sequence numbers continue the
pre-crash stream so the TCP session-resumption layer
(``transport/tcp.py``) neither loses nor double-applies a frame.
"""

from .driver import (
    durable_tcp_node,
    prime_replay,
    restart_tcp_node,
)
from .node import DurableAlgo, Recovery, RecoveryError, recover
from .wal import CHECKPOINT, INPUT, MESSAGE, Record, WalError, WalWriter, read_records

__all__ = [
    "CHECKPOINT",
    "INPUT",
    "MESSAGE",
    "DurableAlgo",
    "Record",
    "Recovery",
    "RecoveryError",
    "WalError",
    "WalWriter",
    "durable_tcp_node",
    "prime_replay",
    "read_records",
    "recover",
    "restart_tcp_node",
]
