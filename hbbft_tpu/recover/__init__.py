"""Crash recovery — durable WAL, checkpoint restore, restart drivers.

The recovery stack in one sentence: every inbound event is CRC-framed
into an append-only write-ahead log *before* it is applied
(``wal.py``), epoch-granular ``checkpoint.save`` snapshots bound the
replay tail (``node.py``), and the restart drivers (``driver.py``)
rebuild a killed node whose transport sequence numbers continue the
pre-crash stream so the TCP session-resumption layer
(``transport/tcp.py``) neither loses nor double-applies a frame.
State transfer (``transfer.py``) covers the one gap frame replay
cannot: a peer dark past the replay-buffer bound fetches a
quorum-verified epoch snapshot and fast-forwards; WAL compaction
(``wal.compact_wal`` / the ``HBBFT_TPU_WAL_COMPACT`` trigger) keeps
the log bounded by dropping records before the last checkpoint.
"""

from .driver import (
    durable_tcp_node,
    prime_replay,
    restart_tcp_node,
)
from .node import DurableAlgo, Recovery, RecoveryError, recover
from .transfer import CatchupManager, SnapshotStore, attach_transfer
from .wal import (
    CHECKPOINT,
    INPUT,
    MESSAGE,
    Record,
    WalError,
    WalWriter,
    compact_records,
    compact_wal,
    read_records,
)

__all__ = [
    "CHECKPOINT",
    "INPUT",
    "MESSAGE",
    "CatchupManager",
    "DurableAlgo",
    "Record",
    "Recovery",
    "RecoveryError",
    "SnapshotStore",
    "WalError",
    "WalWriter",
    "attach_transfer",
    "compact_records",
    "compact_wal",
    "durable_tcp_node",
    "prime_replay",
    "read_records",
    "recover",
    "restart_tcp_node",
]
