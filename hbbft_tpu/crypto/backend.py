"""The ``CryptoBackend`` seam — where device acceleration plugs in.

SURVEY §7's architecture stance: every batchable crypto operation the
protocols need (share verification, RS coding, Merkle hashing) routes
through an ops-backend object carried by ``NetworkInfo``, so the TPU
implementation can replace the heavy math without touching any protocol
state machine.

Three implementations:
- :class:`CpuBackend` — pure-Python/NumPy reference (correctness oracle);
- ``TpuBackend`` (``hbbft_tpu/ops/backend_tpu.py``) — batched JAX
  kernels, same results bit-for-bit;
- a *batched façade* (``hbbft_tpu/harness/batching.py``) that queues
  requests from thousands of co-simulated nodes and flushes them as one
  fused device launch per simulation round.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

from .curve import G1, G2, g1_multi_exp, g2_multi_exp
from .hashing import sha256
from .merkle import MerkleProof, MerkleTree
from .rs import make_codec
from . import threshold as T


class EagerFinalizer:
    """Finalizer-protocol wrapper over an already-computed result.

    Every ``*_async`` backend method returns an object satisfying the
    finalizer protocol: calling it yields the result; ``ready()`` /
    ``poll()`` report — without blocking — whether calling it would
    block.  Host backends compute eagerly, so their finalizers are
    born ready; the device finalizer with a real drain to probe is
    ``ops.packed_msm.ProductFinalizer``."""

    __slots__ = ("_result",)

    def __init__(self, result):
        self._result = result

    def __call__(self):
        return self._result

    def ready(self) -> bool:
        return True

    poll = ready


class CpuBackend:
    """Pure host-side ops backend (the correctness oracle)."""

    name = "cpu"

    # -- hashing / merkle -------------------------------------------------

    def sha256_many(self, items: Sequence[bytes]) -> List[bytes]:
        from .. import native as _native

        if _native.available():
            return _native.sha256_many(list(items))
        return [sha256(b) for b in items]

    def merkle_tree(self, values: List[bytes]) -> MerkleTree:
        return MerkleTree(values)

    # -- erasure coding ---------------------------------------------------

    def rs_codec(self, data_shards: int, parity_shards: int):
        return make_codec(data_shards, parity_shards)

    # -- group MSMs -------------------------------------------------------

    def g1_msm(self, points: Sequence[G1], scalars: Sequence[int]) -> G1:
        return g1_multi_exp(points, scalars)

    def g1_msm_async(self, points: Sequence[G1], scalars: Sequence[int]):
        """Enqueue a G1 MSM, returning a zero-arg finalizer.

        Device backends overlap the MSM with host work between the call
        and the finalize (``ops/packed_msm.py``); the host backend
        computes eagerly — same results, same ordering guarantees.
        Finalizers additionally expose ``ready()``/``poll()`` (see
        :class:`EagerFinalizer`).
        """
        result = self.g1_msm(points, scalars)
        return EagerFinalizer(result)

    def g2_msm(self, points: Sequence[G2], scalars: Sequence[int]) -> G2:
        return g2_multi_exp(points, scalars)

    # -- product-form MSM (the fused flush's dominant shape) ---------------

    def g1_ship(
        self,
        points: Sequence[G1],
        group_sizes: Optional[Sequence[int]] = None,
    ):
        """Begin moving ``points`` toward the MSM execution engine.

        Device backends start the (asynchronous) wire transfer here so
        it overlaps the caller's transcript hashing and coefficient
        derivation; the host backend has nothing to move.
        ``group_sizes`` (when the caller knows the flush's group
        structure) lets a device backend check shape conformance AND
        that the factored path's executables are warm before
        committing bytes to the wire.  The returned handle is accepted
        by :meth:`g1_msm_product_async` in place of the point list."""
        return points

    def g1_msm_product_async(
        self,
        points,
        s_coeffs: Sequence[int],
        t_coeffs: Sequence[int],
        group_sizes: Sequence[int],
    ):
        """Async ``Σ_g t_g · (Σ_{i∈g} sᵢ · Pᵢ)`` over group-major
        ``points`` (``len(points) == sum(group_sizes)``; ``s_coeffs``
        aligned per point, ``t_coeffs`` per group).

        This is the fused flush's product-form aggregate
        (``harness/batching.py``): mathematically equal to one flat MSM
        with coefficients ``sᵢ·t_g mod r``, but the factored shape lets
        a scan-based device kernel run HALF-width scalar muls (s is
        96-bit where s·t is 192) — an advantage bucket-method host
        Pippenger cannot mirror, since it already amortizes doublings.
        Both evaluations agree exactly on r-torsion points (every
        honestly-generated share); off-subgroup forgeries make the
        enclosing check fail under either evaluation (up to the same
        2⁻⁹⁶ Schwartz–Zippel bound), landing in the same per-item
        fallback."""
        points = list(points)
        if not (
            sum(group_sizes) == len(points) == len(s_coeffs)
            and len(t_coeffs) == len(group_sizes)
        ):
            raise ValueError(
                "product MSM shape mismatch: "
                f"{len(points)} points, {len(s_coeffs)} s-coeffs, "
                f"{len(t_coeffs)} t-coeffs over {len(group_sizes)} "
                f"groups summing to {sum(group_sizes)}"
            )
        flat: List[int] = []
        idx = 0
        from . import fields as F

        for t, size in zip(t_coeffs, group_sizes):
            for _ in range(size):
                flat.append((s_coeffs[idx] * t) % F.R)
                idx += 1
        result = self.g1_msm(points, flat)
        return EagerFinalizer(result)

    # -- share verification ------------------------------------------------
    # Every protocol-level share check routes through these two methods
    # (``common_coin.py``, ``honey_badger.py``) so a batching façade can
    # prefetch thousands of them in one fused device launch
    # (``harness/batching.py``) without touching protocol logic.

    def verify_sig_share(self, pk_share, share, msg: bytes) -> bool:
        """Verify one threshold-signature share (reference
        ``common_coin.rs:149-161``)."""
        return pk_share.verify_signature_share(share, msg)

    def verify_dec_share(self, pk_share, share, ciphertext) -> bool:
        """Verify one threshold-decryption share (reference
        ``honey_badger.rs:222-233``)."""
        return pk_share.verify_decryption_share(share, ciphertext)

    # -- batched share verification --------------------------------------

    def batch_verify_shares(
        self,
        shares: Sequence[G1],
        pks: Sequence[G2],
        base: G1,
        context: bytes = b"",
    ) -> bool:
        return T.batch_verify_shares(shares, pks, base, context)


_DEFAULT = CpuBackend()


def default_backend() -> CpuBackend:
    return _DEFAULT


# -- checkpoint restore hook -------------------------------------------------
# Snapshots never serialize a backend (it may hold compiled device
# executables); ``harness/checkpoint.py`` sets this override while
# unpickling so restored ``NetworkInfo`` objects rebind to the caller's
# backend of choice.

_RESTORE_OPS: Any = None


def restore_backend() -> Any:
    return _RESTORE_OPS if _RESTORE_OPS is not None else _DEFAULT


class restore_ops:
    """Context manager: backend to inject into NetworkInfo instances
    restored from a checkpoint within the scope."""

    def __init__(self, ops):
        self.ops = ops

    def __enter__(self):
        global _RESTORE_OPS
        self._prev = _RESTORE_OPS
        _RESTORE_OPS = self.ops
        return self

    def __exit__(self, *exc):
        global _RESTORE_OPS
        _RESTORE_OPS = self._prev
        return False
