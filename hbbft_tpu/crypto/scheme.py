"""Signature-scheme seam — pluggable threshold-signature backends.

The commit-latency literature the harness benchmarks against compares
threshold BLS (one pairing-heavy verify, tiny aggregate) with
committee-style EdDSA batch verification (arXiv:2302.00418: cheaper
per-share verifies, larger certificates).  Everything above this module
talks to the scheme through :class:`SignatureScheme`, so an EdDSA
implementation only has to fill in this interface — no protocol or
harness changes.

Only BLS12-381 is implemented today (it delegates to
``crypto/threshold.py``, including the speculative
``combine_and_check`` surface).  The EdDSA entry is a registered stub:
``get_scheme("eddsa")`` resolves, but using it raises with a pointer to
the comparison it is reserved for.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Sequence

from . import threshold as T


@dataclasses.dataclass(frozen=True)
class SignatureScheme:
    """The operations a threshold-signature backend must provide.

    ``sign_share`` / ``verify_share`` / ``combine`` / ``verify`` are
    the eager per-share surface the protocols use;
    ``batch_verify_shares`` is the fused-flush hook the batching plane
    routes through; ``combine_and_check`` is the speculative
    combine-first surface (PR 10) — schemes without a cheap combined
    check may set it to ``None`` and the callers fall back to eager
    verification.
    """

    name: str
    sign_share: Callable[[Any, bytes], Any]  # (secret_key_share, msg)
    verify_share: Callable[[Any, Any, bytes], bool]  # (pk_share, share, msg)
    combine: Callable[[Any, Dict[int, Any]], Any]  # (pk_set, shares)
    verify: Callable[[Any, Any, bytes], bool]  # (pk_set, sig, msg)
    batch_verify_shares: Optional[Callable[..., bool]] = None
    combine_and_check: Optional[Callable[..., Optional[bytes]]] = None


def _bls_scheme() -> SignatureScheme:
    return SignatureScheme(
        name="bls381",
        sign_share=lambda sks, msg: sks.sign(msg),
        verify_share=lambda pk, share, msg: pk.verify_signature_share(
            share, msg
        ),
        combine=lambda pk_set, shares: pk_set.combine_signatures(shares),
        verify=lambda pk_set, sig, msg: pk_set.verify_signature(sig, msg),
        batch_verify_shares=T.batch_verify_shares,
        combine_and_check=(
            lambda pk_set, shares, ct: pk_set.combine_and_check_decryption_shares(
                shares, ct
            )
        ),
    )


def _eddsa_unavailable(*_args: Any, **_kwargs: Any) -> bool:
    raise NotImplementedError(
        "eddsa scheme is a landing spot only (committee batch-verify "
        "comparison, arXiv:2302.00418); use get_scheme('bls381')"
    )


def _eddsa_scheme() -> SignatureScheme:
    return SignatureScheme(
        name="eddsa",
        sign_share=_eddsa_unavailable,
        verify_share=_eddsa_unavailable,
        combine=_eddsa_unavailable,
        verify=_eddsa_unavailable,
        batch_verify_shares=None,
        combine_and_check=None,
    )


_FACTORIES: Dict[str, Callable[[], SignatureScheme]] = {
    "bls381": _bls_scheme,
    "eddsa": _eddsa_scheme,
}

DEFAULT_SCHEME = "bls381"


def available_schemes() -> Sequence[str]:
    return tuple(sorted(_FACTORIES))


def get_scheme(name: str = DEFAULT_SCHEME) -> SignatureScheme:
    try:
        factory = _FACTORIES[name]
    except KeyError:
        raise ValueError(
            f"unknown signature scheme {name!r}; "
            f"available: {', '.join(available_schemes())}"
        ) from None
    return factory()
