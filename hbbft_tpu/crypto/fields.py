"""BLS12-381 field tower — pure-Python reference implementation.

Replaces the reference's ``pairing`` crate (``Cargo.toml:22``; used via
``threshold_crypto`` everywhere and directly in ``sync_key_gen.rs:160-161``).

Representation choices are deliberately *functional over plain tuples of
ints* rather than classes: it is measurably faster in CPython, and it
mirrors 1:1 the limb-array layout the JAX/TPU kernels use
(``hbbft_tpu/ops/bigint_jax.py``), keeping the CPU reference and device
paths structurally aligned for bit-identity testing.

Tower: Fq2 = Fq[u]/(u²+1);  Fq6 = Fq2[v]/(v³−ξ), ξ=u+1;  Fq12 = Fq6[w]/(w²−v).

All curve constants are verified by arithmetic identities at import time
(cheap asserts) so a mis-remembered constant fails loudly, not subtly.
"""

from __future__ import annotations

from typing import Tuple

# ---------------------------------------------------------------------------
# Constants
# ---------------------------------------------------------------------------

# Base field modulus
P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
# Scalar field modulus (group order r)
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
# BLS curve parameter x (negative); Z is its absolute value.
Z = 0xD201000000010000
X_SIGNED = -Z

# G1 cofactor h1 = (x-1)^2 / 3 and identity p = h1*r + x
H1 = ((X_SIGNED - 1) ** 2) // 3
assert ((X_SIGNED - 1) ** 2) % 3 == 0
assert P == H1 * R + X_SIGNED, "BLS12 parameterisation identity failed"
assert R == Z**4 - Z**2 + 1, "r(x) identity failed"
assert P % 4 == 3 and P % 6 == 1

# G2 cofactor h2 = (x^8 - 4x^7 + 5x^6 - 4x^4 + 6x^3 - 4x^2 - 4x + 13) / 9
H2 = (Z**8 - 4 * Z**7 + 5 * Z**6 - 4 * Z**4 + 6 * Z**3 - 4 * Z**2 - 4 * Z + 13) // 9

Fq = int
Fq2 = Tuple[int, int]
Fq6 = Tuple[Fq2, Fq2, Fq2]
Fq12 = Tuple[Fq6, Fq6]

# ---------------------------------------------------------------------------
# Fq — integers mod P (helpers; mostly inlined at call sites)
# ---------------------------------------------------------------------------


def fq_inv(a: int) -> int:
    return pow(a, -1, P)


def fq_sqrt(a: int) -> int | None:
    """Square root in Fq (p ≡ 3 mod 4): a^((p+1)/4); None if non-residue."""
    r = pow(a, (P + 1) // 4, P)
    return r if r * r % P == a % P else None


# ---------------------------------------------------------------------------
# Fq2
# ---------------------------------------------------------------------------

FQ2_ZERO: Fq2 = (0, 0)
FQ2_ONE: Fq2 = (1, 0)
XI: Fq2 = (1, 1)  # ξ = 1 + u, the Fq6 non-residue


def fq2_add(a: Fq2, b: Fq2) -> Fq2:
    return ((a[0] + b[0]) % P, (a[1] + b[1]) % P)


def fq2_sub(a: Fq2, b: Fq2) -> Fq2:
    return ((a[0] - b[0]) % P, (a[1] - b[1]) % P)


def fq2_neg(a: Fq2) -> Fq2:
    return (-a[0] % P, -a[1] % P)


def fq2_mul(a: Fq2, b: Fq2) -> Fq2:
    a0, a1 = a
    b0, b1 = b
    t0 = a0 * b0
    t1 = a1 * b1
    # (a0+a1)(b0+b1) - t0 - t1 = a0b1 + a1b0
    return ((t0 - t1) % P, ((a0 + a1) * (b0 + b1) - t0 - t1) % P)


def fq2_sq(a: Fq2) -> Fq2:
    a0, a1 = a
    return ((a0 + a1) * (a0 - a1) % P, 2 * a0 * a1 % P)


def fq2_scalar(a: Fq2, k: int) -> Fq2:
    return (a[0] * k % P, a[1] * k % P)


def fq2_conj(a: Fq2) -> Fq2:
    return (a[0], -a[1] % P)


def fq2_inv(a: Fq2) -> Fq2:
    a0, a1 = a
    d = pow(a0 * a0 + a1 * a1, -1, P)
    return (a0 * d % P, -a1 * d % P)


def fq2_mul_xi(a: Fq2) -> Fq2:
    """Multiply by ξ = 1+u: (a0 - a1) + (a0 + a1)u."""
    return ((a[0] - a[1]) % P, (a[0] + a[1]) % P)


def fq2_pow(a: Fq2, e: int) -> Fq2:
    result = FQ2_ONE
    base = a
    while e:
        if e & 1:
            result = fq2_mul(result, base)
        base = fq2_sq(base)
        e >>= 1
    return result


def fq2_sqrt(a: Fq2) -> Fq2 | None:
    """Square root in Fq2 for p ≡ 3 mod 4 (Adj–Rodríguez-Henríquez Alg. 9)."""
    if a == FQ2_ZERO:
        return FQ2_ZERO
    a1 = fq2_pow(a, (P - 3) // 4)
    x0 = fq2_mul(a1, a)
    alpha = fq2_mul(a1, x0)  # a^((p-1)/2)
    if alpha == (P - 1, 0):  # alpha == -1
        x = (-x0[1] % P, x0[0])  # u * x0
    else:
        b = fq2_pow(fq2_add(FQ2_ONE, alpha), (P - 1) // 2)
        x = fq2_mul(b, x0)
    return x if fq2_sq(x) == a else None


# ---------------------------------------------------------------------------
# Fq6 = Fq2[v]/(v³ − ξ)
# ---------------------------------------------------------------------------

FQ6_ZERO: Fq6 = (FQ2_ZERO, FQ2_ZERO, FQ2_ZERO)
FQ6_ONE: Fq6 = (FQ2_ONE, FQ2_ZERO, FQ2_ZERO)


def fq6_add(a: Fq6, b: Fq6) -> Fq6:
    return (fq2_add(a[0], b[0]), fq2_add(a[1], b[1]), fq2_add(a[2], b[2]))


def fq6_sub(a: Fq6, b: Fq6) -> Fq6:
    return (fq2_sub(a[0], b[0]), fq2_sub(a[1], b[1]), fq2_sub(a[2], b[2]))


def fq6_neg(a: Fq6) -> Fq6:
    return (fq2_neg(a[0]), fq2_neg(a[1]), fq2_neg(a[2]))


def fq6_mul(a: Fq6, b: Fq6) -> Fq6:
    a0, a1, a2 = a
    b0, b1, b2 = b
    t0 = fq2_mul(a0, b0)
    t1 = fq2_mul(a1, b1)
    t2 = fq2_mul(a2, b2)
    # c0 = t0 + ξ((a1+a2)(b1+b2) - t1 - t2)
    c0 = fq2_add(
        t0,
        fq2_mul_xi(
            fq2_sub(fq2_sub(fq2_mul(fq2_add(a1, a2), fq2_add(b1, b2)), t1), t2)
        ),
    )
    # c1 = (a0+a1)(b0+b1) - t0 - t1 + ξ t2
    c1 = fq2_add(
        fq2_sub(fq2_sub(fq2_mul(fq2_add(a0, a1), fq2_add(b0, b1)), t0), t1),
        fq2_mul_xi(t2),
    )
    # c2 = (a0+a2)(b0+b2) - t0 - t2 + t1
    c2 = fq2_add(
        fq2_sub(fq2_sub(fq2_mul(fq2_add(a0, a2), fq2_add(b0, b2)), t0), t2), t1
    )
    return (c0, c1, c2)


def fq6_sq(a: Fq6) -> Fq6:
    return fq6_mul(a, a)


def fq6_mul_by_v(a: Fq6) -> Fq6:
    """Multiply by v: (c0,c1,c2) -> (ξ·c2, c0, c1)."""
    return (fq2_mul_xi(a[2]), a[0], a[1])


def fq6_inv(a: Fq6) -> Fq6:
    c0, c1, c2 = a
    t0 = fq2_sub(fq2_sq(c0), fq2_mul_xi(fq2_mul(c1, c2)))
    t1 = fq2_sub(fq2_mul_xi(fq2_sq(c2)), fq2_mul(c0, c1))
    t2 = fq2_sub(fq2_sq(c1), fq2_mul(c0, c2))
    d = fq2_add(
        fq2_mul(c0, t0),
        fq2_mul_xi(fq2_add(fq2_mul(c1, t2), fq2_mul(c2, t1))),
    )
    dinv = fq2_inv(d)
    return (fq2_mul(t0, dinv), fq2_mul(t1, dinv), fq2_mul(t2, dinv))


# ---------------------------------------------------------------------------
# Fq12 = Fq6[w]/(w² − v)
# ---------------------------------------------------------------------------

FQ12_ONE: Fq12 = (FQ6_ONE, FQ6_ZERO)


def fq12_mul(a: Fq12, b: Fq12) -> Fq12:
    a0, a1 = a
    b0, b1 = b
    t0 = fq6_mul(a0, b0)
    t1 = fq6_mul(a1, b1)
    c0 = fq6_add(t0, fq6_mul_by_v(t1))
    c1 = fq6_sub(fq6_sub(fq6_mul(fq6_add(a0, a1), fq6_add(b0, b1)), t0), t1)
    return (c0, c1)


def fq12_sq(a: Fq12) -> Fq12:
    a0, a1 = a
    t = fq6_mul(a0, a1)
    c0 = fq6_sub(
        fq6_sub(fq6_mul(fq6_add(a0, a1), fq6_add(a0, fq6_mul_by_v(a1))), t),
        fq6_mul_by_v(t),
    )
    return (c0, fq6_add(t, t))


def fq12_conj(a: Fq12) -> Fq12:
    """Conjugation = Frobenius^6; equals inverse on the cyclotomic subgroup."""
    return (a[0], fq6_neg(a[1]))


def fq12_inv(a: Fq12) -> Fq12:
    a0, a1 = a
    d = fq6_sub(fq6_sq(a0), fq6_mul_by_v(fq6_sq(a1)))
    dinv = fq6_inv(d)
    return (fq6_mul(a0, dinv), fq6_neg(fq6_mul(a1, dinv)))


def fq12_pow(a: Fq12, e: int) -> Fq12:
    if e < 0:
        a = fq12_inv(a)
        e = -e
    result = FQ12_ONE
    base = a
    while e:
        if e & 1:
            result = fq12_mul(result, base)
        base = fq12_sq(base)
        e >>= 1
    return result


# -- Frobenius --------------------------------------------------------------
# Constants computed (not memorised): γ1 = ξ^((p-1)/6) governs w^p = γ1·w.

_G1C = fq2_pow(XI, (P - 1) // 6)  # ξ^((p-1)/6)
_FROB6_C1 = fq2_pow(XI, (P - 1) // 3)  # v^p = C1 · v
_FROB6_C2 = fq2_pow(XI, 2 * (P - 1) // 3)  # v^{2p} = C2 · v²


def fq6_frobenius(a: Fq6) -> Fq6:
    return (
        fq2_conj(a[0]),
        fq2_mul(fq2_conj(a[1]), _FROB6_C1),
        fq2_mul(fq2_conj(a[2]), _FROB6_C2),
    )


def _fq6_scale_fq2(a: Fq6, s: Fq2) -> Fq6:
    return (fq2_mul(a[0], s), fq2_mul(a[1], s), fq2_mul(a[2], s))


def fq12_frobenius(a: Fq12) -> Fq12:
    c0 = fq6_frobenius(a[0])
    c1 = _fq6_scale_fq2(fq6_frobenius(a[1]), _G1C)
    return (c0, c1)


def fq12_frobenius2(a: Fq12) -> Fq12:
    return fq12_frobenius(fq12_frobenius(a))
