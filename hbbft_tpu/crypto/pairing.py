"""Optimal ate pairing on BLS12-381.

Replaces the pairing engine of the reference's ``pairing`` crate — the
workhorse behind every ``threshold_crypto`` verify call (signature-share
verify ``common_coin.rs:151``, decryption-share verify
``honey_badger.rs:229``, DKG value checks ``sync_key_gen.rs:449``).

Implementation notes:
- Miller loop runs with ``T`` in *affine Fq2 on the twist* (cheap), and
  each line is evaluated at the G1 point as a sparse Fq12 element.
  Lines are scaled by ``w³``; that factor lies in a subfield-torsion
  coset killed by the final exponentiation, so pairing values are
  unaffected (standard trick).
- Final exponentiation uses the cyclotomic decomposition
  ``3·(p⁴−p²+1)/r = (x−1)²·(x+p)·(x²+p²−1) + 3`` (Hayashida–Hayasaka–
  Teruya); we therefore compute the pairing raised to the fixed power 3,
  which (3 ∤ r) is still bilinear and non-degenerate.  The identity is
  asserted at import so the formula cannot silently be wrong.
"""

from __future__ import annotations

from typing import Iterable, List, Tuple

from . import fields as F
from .fields import (
    FQ2_ZERO,
    FQ12_ONE,
    P,
    R,
    X_SIGNED,
    Z,
    fq2_add,
    fq2_inv,
    fq2_mul,
    fq2_neg,
    fq2_scalar,
    fq2_sq,
    fq2_sub,
    fq12_conj,
    fq12_frobenius,
    fq12_frobenius2,
    fq12_inv,
    fq12_mul,
    fq12_sq,
)
from .curve import G1, G2

# Verify the final-exponentiation decomposition at import time.
assert (P**4 - P**2 + 1) % R == 0
assert (
    3 * ((P**4 - P**2 + 1) // R)
    == (X_SIGNED - 1) ** 2 * (X_SIGNED + P) * (X_SIGNED**2 + P**2 - 1) + 3
), "BLS12 hard-part decomposition failed"
assert R % 3 != 0  # cubing is a bijection on the r-torsion of roots of unity

_Z_BITS = [(Z >> i) & 1 for i in range(Z.bit_length() - 2, -1, -1)]


# ---------------------------------------------------------------------------
# Sparse Fq6/Fq12 multiplications for line evaluation
# ---------------------------------------------------------------------------


def _fq6_mul_by_01(c, s0, s1):
    """(c0,c1,c2)·(s0,s1,0) in Fq6."""
    c0, c1, c2 = c
    return (
        fq2_add(fq2_mul(c0, s0), F.fq2_mul_xi(fq2_mul(c2, s1))),
        fq2_add(fq2_mul(c0, s1), fq2_mul(c1, s0)),
        fq2_add(fq2_mul(c1, s1), fq2_mul(c2, s0)),
    )


def _fq6_mul_by_1(c, s1):
    """(c0,c1,c2)·(0,s1,0) in Fq6."""
    c0, c1, c2 = c
    return (F.fq2_mul_xi(fq2_mul(c2, s1)), fq2_mul(c0, s1), fq2_mul(c1, s1))


def _mul_by_line(f, a0, a1, b1):
    """f · l where l = (a0 + a1·v) + (b1·v)·w   (sparse Fq12)."""
    f0, f1 = f
    t0 = _fq6_mul_by_01(f0, a0, a1)
    t1 = _fq6_mul_by_1(f1, b1)
    # c1 = (f0+f1)·(a + b) − t0 − t1, with a+b = (a0, a1+b1, 0)
    fs = F.fq6_add(f0, f1)
    c1 = F.fq6_sub(F.fq6_sub(_fq6_mul_by_01(fs, a0, fq2_add(a1, b1)), t0), t1)
    c0 = F.fq6_add(t0, F.fq6_mul_by_v(t1))
    return (c0, c1)


# ---------------------------------------------------------------------------
# Miller loop
# ---------------------------------------------------------------------------


def _line_dbl(T, xP, yP):
    """Tangent line at T=(X,Y)∈E'(Fq2), evaluated at P=(xP,yP)∈E(Fq).

    Returns (line components (a0,a1,b1), 2T)."""
    X, Y = T
    lam = fq2_mul(fq2_scalar(fq2_sq(X), 3), fq2_inv(fq2_scalar(Y, 2)))
    X3 = fq2_sub(fq2_sq(lam), fq2_scalar(X, 2))
    Y3 = fq2_sub(fq2_mul(lam, fq2_sub(X, X3)), Y)
    a0 = fq2_sub(fq2_mul(lam, X), Y)
    a1 = fq2_scalar(fq2_neg(lam), xP)
    b1 = (yP, 0)
    return (a0, a1, b1), (X3, Y3)


def _line_add(T, Q, xP, yP):
    """Line through T and Q on the twist, evaluated at P."""
    X1, Y1 = T
    X2, Y2 = Q
    lam = fq2_mul(fq2_sub(Y2, Y1), fq2_inv(fq2_sub(X2, X1)))
    X3 = fq2_sub(fq2_sub(fq2_sq(lam), X1), X2)
    Y3 = fq2_sub(fq2_mul(lam, fq2_sub(X1, X3)), Y1)
    a0 = fq2_sub(fq2_mul(lam, X1), Y1)
    a1 = fq2_scalar(fq2_neg(lam), xP)
    b1 = (yP, 0)
    return (a0, a1, b1), (X3, Y3)


def miller_loop(p: G1, q: G2) -> F.Fq12:
    """f_{|x|,Q}(P), conjugated for the negative BLS parameter."""
    paff = p.affine()
    qaff = q.affine()
    if paff is None or qaff is None:
        return FQ12_ONE
    xP, yP = paff
    Q = qaff
    T = Q
    f = FQ12_ONE
    for bit in _Z_BITS:
        f = fq12_sq(f)
        line, T = _line_dbl(T, xP, yP)
        f = _mul_by_line(f, *line)
        if bit:
            line, T = _line_add(T, Q, xP, yP)
            f = _mul_by_line(f, *line)
    return fq12_conj(f)  # parameter x < 0


# ---------------------------------------------------------------------------
# Final exponentiation
# ---------------------------------------------------------------------------


def _exp_by_z(m: F.Fq12) -> F.Fq12:
    """m^Z (Z = |x|) by square-and-multiply; m must be cyclotomic."""
    result = m
    for bit in _Z_BITS:
        result = fq12_sq(result)
        if bit:
            result = fq12_mul(result, m)
    return result


def _exp_by_x(m: F.Fq12) -> F.Fq12:
    """m^x with x = -Z, using conjugation as cyclotomic inverse."""
    return fq12_conj(_exp_by_z(m))


def final_exponentiation(f: F.Fq12) -> F.Fq12:
    """f^{3·(p¹²−1)/r} — the pairing raised to a fixed power coprime to r."""
    # easy part: f^((p^6-1)(p^2+1))
    f = fq12_mul(fq12_conj(f), fq12_inv(f))
    f = fq12_mul(fq12_frobenius2(f), f)
    m = f
    # hard part: m^((x-1)^2 (x+p) (x^2+p^2-1)) · m^3
    t0 = fq12_mul(_exp_by_x(m), fq12_conj(m))  # m^(x-1)
    t0 = fq12_mul(_exp_by_x(t0), fq12_conj(t0))  # m^((x-1)^2)
    t1 = fq12_mul(_exp_by_x(t0), fq12_frobenius(t0))  # t0^(x+p)
    t3 = _exp_by_x(_exp_by_x(t1))  # t1^(x^2)
    out = fq12_mul(fq12_mul(t3, fq12_frobenius2(t1)), fq12_conj(t1))
    return fq12_mul(out, fq12_mul(m, fq12_sq(m)))


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------


def _native():
    from .. import native as NT

    return NT.backend()


def _fq12_from_bytes(raw: bytes) -> F.Fq12:
    v = [int.from_bytes(raw[i * 48 : (i + 1) * 48], "big") for i in range(12)]
    return (
        ((v[0], v[1]), (v[2], v[3]), (v[4], v[5])),
        ((v[6], v[7]), (v[8], v[9]), (v[10], v[11])),
    )


def pairing(p: G1, q: G2) -> F.Fq12:
    """e(P, Q)³ — bilinear, non-degenerate; canonical for equality checks.

    The native path returns byte-identical Fq12 values (its projective
    Miller-loop lines differ from the affine ones here only by Fq2*
    factors, which the final exponentiation kills)."""
    nt = _native()
    if nt is not None:
        return _fq12_from_bytes(nt.pairing_bytes(nt.g1_wire(p), nt.g2_wire(q)))
    return final_exponentiation(miller_loop(p, q))


def pairing_check(pairs: Iterable[Tuple[G1, G2]]) -> bool:
    """True iff Π e(Pᵢ, Qᵢ) == 1.

    One shared final exponentiation over the product of Miller loops —
    this is what makes batched (random-linear-combination) share
    verification cheap on the host side.
    """
    pairs = list(pairs)
    nt = _native()
    if nt is not None:
        return nt.pairing_check(
            [nt.g1_wire(p) for p, _ in pairs], [nt.g2_wire(q) for _, q in pairs]
        )
    acc = FQ12_ONE
    for p, q in pairs:
        acc = fq12_mul(acc, miller_loop(p, q))
    return final_exponentiation(acc) == FQ12_ONE


def pairings_equal(p1: G1, q1: G2, p2: G1, q2: G2) -> bool:
    """e(P1,Q1) == e(P2,Q2), via a single product check."""
    return pairing_check([(p1, q1), (-p2, q2)])
