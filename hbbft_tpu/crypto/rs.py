"""Systematic Reed-Solomon erasure coding over GF(2^8).

Replaces the ``reed-solomon-erasure`` crate (``Cargo.toml:26``; encode at
``broadcast.rs:365-367``, reconstruct at ``broadcast.rs:643-656``).

Encoding is a GF(2^8) matrix multiply — the representation is chosen so
the TPU path (``ops/gf256_jax.py``) runs the *same* systematic matrix as
one batched log/antilog-table matmul.  The systematic generator matrix is
a Vandermonde matrix normalised so the top k×k block is the identity
(Backblaze/Plank construction, matching the reference crate's family).

The f = 0 edge case (single data shard per node, no parity) mirrors the
reference's ``Coding::Trivial`` fallback (``broadcast.rs:596-658``).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

# --- GF(2^8) tables, primitive polynomial 0x11d, generator 3 ----------------

_EXP = np.zeros(512, dtype=np.uint8)
_LOG = np.zeros(256, dtype=np.int32)


def _build_tables() -> None:
    x = 1
    for i in range(255):
        _EXP[i] = x
        _LOG[x] = i
        x <<= 1
        if x & 0x100:
            x ^= 0x11D
    for i in range(255, 512):
        _EXP[i] = _EXP[i - 255]


_build_tables()


def gf_mul(a: int, b: int) -> int:
    if a == 0 or b == 0:
        return 0
    return int(_EXP[int(_LOG[a]) + int(_LOG[b])])


def gf_inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("GF(256) inverse of 0")
    return int(_EXP[255 - int(_LOG[a])])


def gf_matmul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """(m,k)·(k,n) GF(2^8) matrix product, fully vectorised."""
    a = np.asarray(a, dtype=np.uint8)
    b = np.asarray(b, dtype=np.uint8)
    la = _LOG[a]  # (m, k)
    lb = _LOG[b]  # (k, n)
    prod = _EXP[(la[:, :, None] + lb[None, :, :])]
    prod = np.where((a[:, :, None] == 0) | (b[None, :, :] == 0), 0, prod)
    return np.bitwise_xor.reduce(prod, axis=1).astype(np.uint8)


def _gf_mat_inv(m: np.ndarray) -> np.ndarray:
    """Gauss-Jordan inversion over GF(2^8)."""
    n = m.shape[0]
    aug = np.concatenate([m.astype(np.uint8), np.eye(n, dtype=np.uint8)], axis=1)
    for col in range(n):
        pivot = None
        for row in range(col, n):
            if aug[row, col] != 0:
                pivot = row
                break
        if pivot is None:
            raise ValueError("matrix not invertible over GF(256)")
        if pivot != col:
            aug[[col, pivot]] = aug[[pivot, col]]
        inv_p = gf_inv(int(aug[col, col]))
        # scale pivot row
        row_vals = aug[col]
        scaled = np.where(
            row_vals == 0, 0, _EXP[_LOG[row_vals] + _LOG[inv_p]]
        ).astype(np.uint8)
        aug[col] = scaled
        for row in range(n):
            if row != col and aug[row, col] != 0:
                factor = int(aug[row, col])
                mult = np.where(
                    aug[col] == 0, 0, _EXP[_LOG[aug[col]] + _LOG[factor]]
                ).astype(np.uint8)
                aug[row] ^= mult
    return aug[:, n:]


def _matmul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """GF(2^8) matmul via the C++ native library when loaded, else the
    vectorised NumPy path (the semantics oracle)."""
    from .. import native as _native

    if _native.available():
        return _native.gf_matmul(a, b)
    return gf_matmul(a, b)


def _mat_inv(m: np.ndarray) -> np.ndarray:
    from .. import native as _native

    if _native.available():
        return _native.gf_mat_inv(m)
    return _gf_mat_inv(m)


_MATRIX_CACHE: dict = {}


def _systematic_matrix(k: int, n: int) -> np.ndarray:
    """n×k systematic generator matrix (top k×k = identity)."""
    key = (k, n)
    cached = _MATRIX_CACHE.get(key)
    if cached is not None:
        return cached
    # Vandermonde rows: row i = [1, aᵢ, aᵢ², …] with distinct aᵢ = i.
    # Any k rows are linearly independent, so after normalisation any k
    # shards suffice for reconstruction.
    vand = np.zeros((n, k), dtype=np.uint8)
    for i in range(n):
        v = 1
        for j in range(k):
            vand[i, j] = v
            v = gf_mul(v, i)
    # normalise: M = V · (top k×k)^-1  → systematic
    top_inv = _gf_mat_inv(vand[:k, :k].copy())
    mat = gf_matmul(vand, top_inv)
    _MATRIX_CACHE[key] = mat
    return mat


class ReedSolomon:
    """Systematic RS codec: k data shards, n total (n−k parity).

    Same interface shape as the reference's ``Coding`` wrapper
    (``broadcast.rs:596-658``): ``encode`` fills parity from data,
    ``reconstruct`` recovers all shards from any k of them.
    """

    def __init__(self, data_shards: int, parity_shards: int):
        if data_shards < 1:
            raise ValueError("need at least one data shard")
        if data_shards + parity_shards > 256:
            raise ValueError("GF(256) supports at most 256 shards")
        self.k = data_shards
        self.m = parity_shards
        self.n = data_shards + parity_shards
        self.matrix = (
            _systematic_matrix(self.k, self.n) if parity_shards > 0 else None
        )

    def encode(self, data: Sequence[bytes]) -> List[bytes]:
        """data: k equal-length shards → n shards (data ++ parity)."""
        if len(data) != self.k:
            raise ValueError(f"expected {self.k} data shards")
        if self.m == 0:
            return list(data)
        arr = np.frombuffer(b"".join(data), dtype=np.uint8).reshape(
            self.k, -1
        )
        parity = _matmul(self.matrix[self.k :], arr)
        return list(data) + [p.tobytes() for p in parity]

    def reconstruct(self, shards: List[Optional[bytes]]) -> List[bytes]:
        """Recover all n shards; ``shards[i] is None`` marks an erasure.
        Raises ValueError with fewer than k present."""
        if len(shards) != self.n:
            raise ValueError(f"expected {self.n} shard slots")
        present = [i for i, s in enumerate(shards) if s is not None]
        if len(present) < self.k:
            raise ValueError("not enough shards to reconstruct")
        if self.m == 0:
            return [s for s in shards]  # type: ignore[misc]
        use = present[: self.k]
        sub = self.matrix[use, :]
        dec = _mat_inv(sub.copy())
        avail = np.stack(
            [np.frombuffer(shards[i], dtype=np.uint8) for i in use]
        )
        data = _matmul(dec, avail)
        full = _matmul(self.matrix, data)
        out: List[bytes] = []
        for i in range(self.n):
            out.append(
                shards[i] if shards[i] is not None else full[i].tobytes()
            )
        return out
