"""Systematic Reed-Solomon erasure coding over GF(2^8) and GF(2^16).

Replaces the ``reed-solomon-erasure`` crate (``Cargo.toml:26``; encode at
``broadcast.rs:365-367``, reconstruct at ``broadcast.rs:643-656``).

Encoding is a GF(2^w) matrix multiply — the representation is chosen so
the TPU path (``ops/gf256_jax.py``) runs the *same* systematic matrix as
one batched bit-sliced matmul.  The systematic generator matrix is
a Vandermonde matrix normalised so the top k×k block is the identity
(Backblaze/Plank construction, matching the reference crate's family).

The reference crate is GF(2^8)-only, capping reliable broadcast at 256
shards = 256 validators; :class:`ReedSolomon16` lifts the north-star
1024-validator configuration past that cap with 16-bit symbols (up to
65536 shards) under the identical construction.  :func:`make_codec`
picks the narrowest field that fits.

The f = 0 edge case (single data shard per node, no parity) mirrors the
reference's ``Coding::Trivial`` fallback (``broadcast.rs:596-658``).
"""

from __future__ import annotations

import threading
from typing import List, Optional, Sequence

import numpy as np

# --- GF(2^8) tables, primitive polynomial 0x11d, generator 3 ----------------

_EXP = np.zeros(512, dtype=np.uint8)
_LOG = np.zeros(256, dtype=np.int32)


def _build_tables() -> None:
    x = 1
    for i in range(255):
        _EXP[i] = x
        _LOG[x] = i
        x <<= 1
        if x & 0x100:
            x ^= 0x11D
    for i in range(255, 512):
        _EXP[i] = _EXP[i - 255]


_build_tables()


def gf_mul(a: int, b: int) -> int:
    if a == 0 or b == 0:
        return 0
    return int(_EXP[int(_LOG[a]) + int(_LOG[b])])


def gf_inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("GF(256) inverse of 0")
    return int(_EXP[255 - int(_LOG[a])])


def gf_matmul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """(m,k)·(k,n) GF(2^8) matrix product, fully vectorised."""
    a = np.asarray(a, dtype=np.uint8)
    b = np.asarray(b, dtype=np.uint8)
    la = _LOG[a]  # (m, k)
    lb = _LOG[b]  # (k, n)
    prod = _EXP[(la[:, :, None] + lb[None, :, :])]
    prod = np.where((a[:, :, None] == 0) | (b[None, :, :] == 0), 0, prod)
    return np.bitwise_xor.reduce(prod, axis=1).astype(np.uint8)


def _gf_mat_inv(m: np.ndarray) -> np.ndarray:
    """Gauss-Jordan inversion over GF(2^8)."""
    n = m.shape[0]
    aug = np.concatenate([m.astype(np.uint8), np.eye(n, dtype=np.uint8)], axis=1)
    for col in range(n):
        pivot = None
        for row in range(col, n):
            if aug[row, col] != 0:
                pivot = row
                break
        if pivot is None:
            raise ValueError("matrix not invertible over GF(256)")
        if pivot != col:
            aug[[col, pivot]] = aug[[pivot, col]]
        inv_p = gf_inv(int(aug[col, col]))
        # scale pivot row
        row_vals = aug[col]
        scaled = np.where(
            row_vals == 0, 0, _EXP[_LOG[row_vals] + _LOG[inv_p]]
        ).astype(np.uint8)
        aug[col] = scaled
        for row in range(n):
            if row != col and aug[row, col] != 0:
                factor = int(aug[row, col])
                mult = np.where(
                    aug[col] == 0, 0, _EXP[_LOG[aug[col]] + _LOG[factor]]
                ).astype(np.uint8)
                aug[row] ^= mult
    return aug[:, n:]


def _matmul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """GF(2^8) matmul via the C++ native library when loaded, else the
    vectorised NumPy path (the semantics oracle)."""
    from .. import native as _native

    if _native.available():
        return _native.gf_matmul(a, b)
    return gf_matmul(a, b)


def _mat_inv(m: np.ndarray) -> np.ndarray:
    from .. import native as _native

    if _native.available():
        return _native.gf_mat_inv(m)
    return _gf_mat_inv(m)


_MATRIX_CACHE: dict = {}


def _systematic_matrix(k: int, n: int) -> np.ndarray:
    """n×k systematic generator matrix (top k×k = identity)."""
    key = (k, n)
    cached = _MATRIX_CACHE.get(key)
    if cached is not None:
        return cached
    # Vandermonde rows: row i = [1, aᵢ, aᵢ², …] with distinct aᵢ = i.
    # Any k rows are linearly independent, so after normalisation any k
    # shards suffice for reconstruction.
    vand = np.zeros((n, k), dtype=np.uint8)
    for i in range(n):
        v = 1
        for j in range(k):
            vand[i, j] = v
            v = gf_mul(v, i)
    # normalise: M = V · (top k×k)^-1  → systematic
    top_inv = _gf_mat_inv(vand[:k, :k].copy())
    mat = gf_matmul(vand, top_inv)
    _MATRIX_CACHE[key] = mat
    return mat


class ReedSolomon:
    """Systematic RS codec: k data shards, n total (n−k parity).

    Same interface shape as the reference's ``Coding`` wrapper
    (``broadcast.rs:596-658``): ``encode`` fills parity from data,
    ``reconstruct`` recovers all shards from any k of them.
    """

    symbol = 1  # bytes per code symbol (shard lengths must be multiples)

    def __init__(self, data_shards: int, parity_shards: int):
        if data_shards < 1:
            raise ValueError("need at least one data shard")
        if data_shards + parity_shards > 256:
            raise ValueError("GF(256) supports at most 256 shards")
        self.k = data_shards
        self.m = parity_shards
        self.n = data_shards + parity_shards
        self.matrix = (
            _systematic_matrix(self.k, self.n) if parity_shards > 0 else None
        )
        self._dec_cache: dict = {}  # present-subset → inverted submatrix

    def decode_matrix(self, use: Sequence[int]) -> np.ndarray:
        key = tuple(use)
        dec = self._dec_cache.get(key)
        if dec is None:
            dec = _mat_inv(self.matrix[list(use), :].copy())
            if len(self._dec_cache) >= 16:
                self._dec_cache.pop(next(iter(self._dec_cache)))
            self._dec_cache[key] = dec
        return dec

    def encode(self, data: Sequence[bytes]) -> List[bytes]:
        """data: k equal-length shards → n shards (data ++ parity)."""
        if len(data) != self.k:
            raise ValueError(f"expected {self.k} data shards")
        if self.m == 0:
            return list(data)
        arr = np.frombuffer(b"".join(data), dtype=np.uint8).reshape(
            self.k, -1
        )
        parity = _matmul(self.matrix[self.k :], arr)
        return list(data) + [p.tobytes() for p in parity]

    def reconstruct(self, shards: List[Optional[bytes]]) -> List[bytes]:
        """Recover all n shards; ``shards[i] is None`` marks an erasure.
        Raises ValueError with fewer than k present."""
        if len(shards) != self.n:
            raise ValueError(f"expected {self.n} shard slots")
        present = [i for i, s in enumerate(shards) if s is not None]
        if len(present) < self.k:
            raise ValueError("not enough shards to reconstruct")
        if self.m == 0:
            return [s for s in shards]  # type: ignore[misc]
        use = present[: self.k]
        dec = self.decode_matrix(use)
        avail = np.stack(
            [np.frombuffer(shards[i], dtype=np.uint8) for i in use]
        )
        data = _matmul(dec, avail)
        # recompute only the erased rows (matches the GF(2^16) codec and
        # the device codecs; present shards pass through untouched)
        missing = [i for i, s in enumerate(shards) if s is None]
        out: List[Optional[bytes]] = list(shards)
        if missing:
            rec = _matmul(self.matrix[missing, :], data)
            for j, i in enumerate(missing):
                out[i] = rec[j].tobytes()
        return out  # type: ignore[return-value]


# --- GF(2^16), primitive polynomial 0x1100B, generator 3 ---------------------
# Same log/antilog construction as GF(2^8) above, with 16-bit symbols;
# tables are built lazily (65535 iterations) on first use of a >256-shard
# codec so the common reference-parity path pays nothing.

_EXP16: Optional[np.ndarray] = None
_LOG16: Optional[np.ndarray] = None
# the epoch driver's stage worker runs RS encodes concurrently with
# the main thread's decodes — the lazy build must not be torn
_TABLE16_LOCK = threading.Lock()


def _build_tables16() -> None:
    global _EXP16, _LOG16
    if _EXP16 is not None:
        return
    with _TABLE16_LOCK:
        if _EXP16 is not None:
            return
        exp = np.zeros(2 * 65535, dtype=np.uint16)
        log = np.zeros(65536, dtype=np.int32)
        x = 1
        for i in range(65535):
            exp[i] = x
            log[x] = i
            x <<= 1
            if x & 0x10000:
                x ^= 0x1100B
        exp[65535:] = exp[:65535]
        # publish LOG16 first: readers gate on _EXP16 being non-None
        _LOG16 = log
        _EXP16 = exp


def gf16_mul(a: int, b: int) -> int:
    _build_tables16()
    if a == 0 or b == 0:
        return 0
    return int(_EXP16[int(_LOG16[a]) + int(_LOG16[b])])


def gf16_inv(a: int) -> int:
    _build_tables16()
    if a == 0:
        raise ZeroDivisionError("GF(2^16) inverse of 0")
    return int(_EXP16[65535 - int(_LOG16[a])])


def gf16_matmul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """(m,k)·(k,n) GF(2^16) matrix product, chunked over rows so the
    (rows, k, n) log-sum intermediate stays within a fixed memory
    budget at bench shapes (e.g. 682×342 times 342×500k symbols for a
    1 MB broadcast at n=1024)."""
    _build_tables16()
    a = np.asarray(a, dtype=np.uint16)
    b = np.asarray(b, dtype=np.uint16)
    m, k = a.shape
    n = b.shape[1]
    out = np.zeros((m, n), dtype=np.uint16)
    lb = _LOG16[b]  # (k, n)
    bz = b == 0  # (k, n)
    # ~32M int32 intermediate elements per chunk
    rows = max(1, (32 << 20) // max(1, k * n))
    for r0 in range(0, m, rows):
        sl = slice(r0, min(r0 + rows, m))
        la = _LOG16[a[sl]]  # (r, k)
        prod = _EXP16[(la[:, :, None] + lb[None, :, :])]
        prod = np.where((a[sl][:, :, None] == 0) | bz[None, :, :], 0, prod)
        out[sl] = np.bitwise_xor.reduce(prod, axis=1)
    return out


def _gf16_mat_inv(m: np.ndarray) -> np.ndarray:
    """Gauss-Jordan inversion over GF(2^16)."""
    _build_tables16()
    n = m.shape[0]
    aug = np.concatenate(
        [m.astype(np.uint16), np.eye(n, dtype=np.uint16)], axis=1
    )
    for col in range(n):
        pivot = None
        for row in range(col, n):
            if aug[row, col] != 0:
                pivot = row
                break
        if pivot is None:
            raise ValueError("matrix not invertible over GF(2^16)")
        if pivot != col:
            aug[[col, pivot]] = aug[[pivot, col]]
        inv_p = gf16_inv(int(aug[col, col]))
        row_vals = aug[col]
        scaled = np.where(
            row_vals == 0, 0, _EXP16[_LOG16[row_vals] + _LOG16[inv_p]]
        ).astype(np.uint16)
        aug[col] = scaled
        for row in range(n):
            if row != col and aug[row, col] != 0:
                factor = int(aug[row, col])
                mult = np.where(
                    aug[col] == 0, 0, _EXP16[_LOG16[aug[col]] + _LOG16[factor]]
                ).astype(np.uint16)
                aug[row] ^= mult
    return aug[:, n:]


def _matmul16(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """GF(2^16) matmul via the C++ native library (AVX2 nibble-table
    row kernel) when loaded, else the chunked NumPy oracle."""
    from .. import native as _native

    if _native.available():
        return _native.gf16_matmul(a, b)
    return gf16_matmul(a, b)


def _mat_inv16(m: np.ndarray) -> np.ndarray:
    from .. import native as _native

    if _native.available():
        return _native.gf16_mat_inv(m)
    return _gf16_mat_inv(m)


_MATRIX16_CACHE: dict = {}


def _systematic_matrix16(k: int, n: int) -> np.ndarray:
    """n×k systematic generator matrix over GF(2^16)."""
    key = (k, n)
    cached = _MATRIX16_CACHE.get(key)
    if cached is not None:
        return cached
    _build_tables16()
    vand = np.zeros((n, k), dtype=np.uint16)
    for i in range(n):
        v = 1
        for j in range(k):
            vand[i, j] = v
            v = gf16_mul(v, i)
    top_inv = _gf16_mat_inv(vand[:k, :k].copy())
    mat = gf16_matmul(vand, top_inv)
    _MATRIX16_CACHE[key] = mat
    return mat


class ReedSolomon16:
    """Systematic RS codec over GF(2^16): up to 65536 shards.

    Interface-identical to :class:`ReedSolomon`; shard byte lengths must
    be multiples of ``symbol`` = 2 (the broadcast framing rounds shard
    sizes up to the codec's symbol, ``protocols/broadcast.py``).
    """

    symbol = 2

    def __init__(self, data_shards: int, parity_shards: int):
        if data_shards < 1:
            raise ValueError("need at least one data shard")
        if data_shards + parity_shards > 65536:
            raise ValueError("GF(2^16) supports at most 65536 shards")
        self.k = data_shards
        self.m = parity_shards
        self.n = data_shards + parity_shards
        self.matrix = (
            _systematic_matrix16(self.k, self.n) if parity_shards > 0 else None
        )
        # decode matrices keyed by the present-shard subset: a co-simulated
        # epoch decodes N broadcasts against one erasure pattern, and the
        # O(k³) Gauss-Jordan dominated the profile without this
        self._dec_cache: dict = {}

    def decode_matrix(self, use: Sequence[int]) -> np.ndarray:
        key = tuple(use)
        dec = self._dec_cache.get(key)
        if dec is None:
            dec = _mat_inv16(self.matrix[list(use), :].copy())
            if len(self._dec_cache) >= 16:
                self._dec_cache.pop(next(iter(self._dec_cache)))
            self._dec_cache[key] = dec
        return dec

    def _to_syms(self, shard: bytes) -> np.ndarray:
        if len(shard) % 2:
            raise ValueError(
                "GF(2^16) shards must have even byte length "
                f"(got {len(shard)})"
            )
        return np.frombuffer(shard, dtype="<u2")

    def encode(self, data: Sequence[bytes]) -> List[bytes]:
        """data: k equal-length shards → n shards (data ++ parity)."""
        if len(data) != self.k:
            raise ValueError(f"expected {self.k} data shards")
        if self.m == 0:
            return list(data)
        arr = np.stack([self._to_syms(s) for s in data])
        parity = _matmul16(self.matrix[self.k :], arr)
        return list(data) + [
            p.astype("<u2").tobytes() for p in parity
        ]

    def reconstruct(self, shards: List[Optional[bytes]]) -> List[bytes]:
        """Recover all n shards; ``shards[i] is None`` marks an erasure."""
        if len(shards) != self.n:
            raise ValueError(f"expected {self.n} shard slots")
        present = [i for i, s in enumerate(shards) if s is not None]
        if len(present) < self.k:
            raise ValueError("not enough shards to reconstruct")
        if self.m == 0:
            return [s for s in shards]  # type: ignore[misc]
        use = present[: self.k]
        dec = self.decode_matrix(use)
        avail = np.stack([self._to_syms(shards[i]) for i in use])
        data = _matmul16(dec, avail)
        missing = [i for i, s in enumerate(shards) if s is None]
        out: List[Optional[bytes]] = list(shards)
        if missing:
            rec = _matmul16(self.matrix[missing, :], data)
            for j, i in enumerate(missing):
                out[i] = rec[j].astype("<u2").tobytes()
        return out  # type: ignore[return-value]


def make_codec(data_shards: int, parity_shards: int):
    """The narrowest field that fits ``data+parity`` shards: GF(2^8)
    (byte-compatible with the reference crate) up to 256, GF(2^16)
    beyond — the north-star N=1024 broadcast path."""
    if data_shards + parity_shards <= 256:
        return ReedSolomon(data_shards, parity_shards)
    return ReedSolomon16(data_shards, parity_shards)
