"""BLS12-381 G1/G2 group arithmetic and zcash-format serialization.

Replaces the curve-group layer of the reference's ``pairing`` crate
(used by ``threshold_crypto`` for every key/signature/ciphertext type,
and directly by the DKG at ``sync_key_gen.rs:160-161``).

Points are Jacobian ``(X, Y, Z)`` tuples over the respective field
(``Z == 0`` ⇒ infinity); one shared formula source is instantiated per
field by :func:`_jacobian_ops` so G1 (over Fq) and G2 (over Fq2) cannot
drift apart.  Compressed serialization follows the zcash BLS12-381
convention (48-byte G1 / 96-byte G2, flag bits 0x80/0x40/0x20).
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

from . import fields as F
from ..core.serialize import wire


def _native():
    """The C++ BLS backend (native/bls12_381.cpp) or None.

    Dispatch happens at call sites — not in the op tables — so the
    pure-Python oracle below stays importable and testable with
    ``HBBFT_TPU_NO_NATIVE=1``."""
    from .. import native as NT

    return NT.backend()

# ---------------------------------------------------------------------------
# Generic Jacobian arithmetic over a field given by its op table
# ---------------------------------------------------------------------------


def _jacobian_ops(zero, one, add, sub, neg, mul, sq, scalar, inv, eq):
    """Build Jacobian point ops for y² = x³ + b over an abstract field."""

    INF = (zero, one, zero)

    def is_inf(p):
        return eq(p[2], zero)

    def double(p):
        X1, Y1, Z1 = p
        if eq(Z1, zero) or eq(Y1, zero):
            return INF
        A = sq(X1)
        B = sq(Y1)
        C = sq(B)
        D = scalar(sub(sub(sq(add(X1, B)), A), C), 2)
        E = scalar(A, 3)
        Fv = sq(E)
        X3 = sub(Fv, scalar(D, 2))
        Y3 = sub(mul(E, sub(D, X3)), scalar(C, 8))
        Z3 = scalar(mul(Y1, Z1), 2)
        return (X3, Y3, Z3)

    def padd(p, q):
        if eq(p[2], zero):
            return q
        if eq(q[2], zero):
            return p
        X1, Y1, Z1 = p
        X2, Y2, Z2 = q
        Z1Z1 = sq(Z1)
        Z2Z2 = sq(Z2)
        U1 = mul(X1, Z2Z2)
        U2 = mul(X2, Z1Z1)
        S1 = mul(mul(Y1, Z2), Z2Z2)
        S2 = mul(mul(Y2, Z1), Z1Z1)
        if eq(U1, U2):
            if eq(S1, S2):
                return double(p)
            return INF
        H = sub(U2, U1)
        I = sq(scalar(H, 2))
        J = mul(H, I)
        rr = scalar(sub(S2, S1), 2)
        V = mul(U1, I)
        X3 = sub(sub(sq(rr), J), scalar(V, 2))
        Y3 = sub(mul(rr, sub(V, X3)), scalar(mul(S1, J), 2))
        Z3 = mul(sub(sub(sq(add(Z1, Z2)), Z1Z1), Z2Z2), H)
        return (X3, Y3, Z3)

    def pneg(p):
        return (p[0], neg(p[1]), p[2])

    def mul_raw(p, k: int):
        if k == 0 or eq(p[2], zero):
            return INF
        result = INF
        bit = 1 << (k.bit_length() - 1)
        while bit:
            result = double(result)
            if k & bit:
                result = padd(result, p)
            bit >>= 1
        return result

    def mul_scalar(p, k: int):
        # Protocol scalars live in Fr; reduce before the double-and-add.
        return mul_raw(p, k % F.R)

    def to_affine(p):
        if eq(p[2], zero):
            return None
        zinv = inv(p[2])
        zinv2 = sq(zinv)
        return (mul(p[0], zinv2), mul(mul(p[1], zinv), zinv2))

    def batch_to_affine(pts):
        """Affine forms of many points with ONE field inversion
        (Montgomery's trick): prefix products of the Z coordinates,
        a single ``inv`` of the running product, then a back-sweep
        peeling off each 1/Zᵢ with two muls.  Every field op is
        canonical (reduced representatives), so each recovered
        inverse equals ``inv(Zᵢ)`` exactly and the output is
        bit-identical to per-point :func:`to_affine`."""
        idx = [i for i, p in enumerate(pts) if not eq(p[2], zero)]
        out = [None] * len(pts)
        if not idx:
            return out
        zs = [pts[i][2] for i in idx]
        prefix = []
        acc = None
        for z in zs:
            acc = z if acc is None else mul(acc, z)
            prefix.append(acc)
        acc = inv(prefix[-1])
        for j in range(len(idx) - 1, -1, -1):
            zinv = mul(acc, prefix[j - 1]) if j else acc
            acc = mul(acc, zs[j])
            p = pts[idx[j]]
            zinv2 = sq(zinv)
            out[idx[j]] = (mul(p[0], zinv2), mul(mul(p[1], zinv), zinv2))
        return out

    def from_affine(a):
        if a is None:
            return INF
        return (a[0], a[1], one)

    def point_eq(p, q):
        pi, qi = eq(p[2], zero), eq(q[2], zero)
        if pi or qi:
            return pi and qi
        # X1·Z2² == X2·Z1², Y1·Z2³ == Y2·Z1³
        Z1Z1, Z2Z2 = sq(p[2]), sq(q[2])
        if not eq(mul(p[0], Z2Z2), mul(q[0], Z1Z1)):
            return False
        return eq(mul(mul(p[1], q[2]), Z2Z2), mul(mul(q[1], p[2]), Z1Z1))

    return {
        "INF": INF,
        "is_inf": is_inf,
        "mul_raw": mul_raw,
        "double": double,
        "add": padd,
        "neg": pneg,
        "mul": mul_scalar,
        "to_affine": to_affine,
        "batch_to_affine": batch_to_affine,
        "from_affine": from_affine,
        "eq": point_eq,
    }


# Fq op table ---------------------------------------------------------------

_fq_ops = _jacobian_ops(
    zero=0,
    one=1,
    add=lambda a, b: (a + b) % F.P,
    sub=lambda a, b: (a - b) % F.P,
    neg=lambda a: -a % F.P,
    mul=lambda a, b: a * b % F.P,
    sq=lambda a: a * a % F.P,
    scalar=lambda a, k: a * k % F.P,
    inv=F.fq_inv,
    eq=lambda a, b: a == b,
)

_fq2_ops = _jacobian_ops(
    zero=F.FQ2_ZERO,
    one=F.FQ2_ONE,
    add=F.fq2_add,
    sub=F.fq2_sub,
    neg=F.fq2_neg,
    mul=F.fq2_mul,
    sq=F.fq2_sq,
    scalar=F.fq2_scalar,
    inv=F.fq2_inv,
    eq=lambda a, b: a == b,
)

B1 = 4  # G1: y² = x³ + 4
B2 = F.fq2_scalar(F.XI, 4)  # G2: y² = x³ + 4(1+u)

# Generators (standard BLS12-381 generators; verified on-curve below).
_G1_X = 0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB
_G1_Y = 0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1
_G2_X = (
    0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
    0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E,
)
_G2_Y = (
    0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
    0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE,
)

assert (_G1_Y * _G1_Y - (_G1_X**3 + B1)) % F.P == 0, "G1 generator not on curve"
assert F.fq2_sub(
    F.fq2_sq(_G2_Y), F.fq2_add(F.fq2_mul(F.fq2_sq(_G2_X), _G2_X), B2)
) == F.FQ2_ZERO, "G2 generator not on curve"


def _is_lex_largest_fq(y: int) -> bool:
    return y > F.P - y


def _is_lex_largest_fq2(y: F.Fq2) -> bool:
    ny = F.fq2_neg(y)
    return (y[1], y[0]) > (ny[1], ny[0])


class _Point:
    """Shared wrapper over Jacobian tuples; subclassed per group."""

    # _wire: lazily-memoized native wire encoding (a pure function of
    # the immutable jac — repeated MSMs over the same points, e.g. the
    # 1024 evaluations of one polynomial commitment during key dealing,
    # paid an Fq/Fq2 inversion per call without it)
    __slots__ = ("jac", "_wire", "_cbytes")
    ops: dict
    b: Any

    def __init__(self, jac):
        self.jac = jac

    # group ops -----------------------------------------------------------

    def __add__(self, other):
        return type(self)(self.ops["add"](self.jac, other.jac))

    def __sub__(self, other):
        return self + (-other)

    def __neg__(self):
        return type(self)(self.ops["neg"](self.jac))

    def __mul__(self, k: int):
        nt = _native()
        if nt is not None:
            return self._native_mul(nt, int(k) % F.R)
        return type(self)(self.ops["mul"](self.jac, k))

    __rmul__ = __mul__

    def double(self):
        return type(self)(self.ops["double"](self.jac))

    def is_infinity(self) -> bool:
        return self.ops["is_inf"](self.jac)

    def affine(self):
        return self.ops["to_affine"](self.jac)

    def to_bytes(self) -> bytes:
        """Canonical compressed encoding, memoized: the batching
        layer keys caches and Fiat-Shamir transcripts by point bytes —
        at epoch scale every share is serialized at least twice and
        each public key thousands of times (points are immutable;
        operations return new objects)."""
        cached = getattr(self, "_cbytes", None)
        if cached is None:
            cached = self._encode()
            self._cbytes = cached
        return cached

    @classmethod
    def batch_affine(cls, points):
        """Affine forms of many points sharing ONE field inversion
        (Montgomery batch inversion) — bit-identical to per-point
        :meth:`affine`."""
        return cls.ops["batch_to_affine"]([p.jac for p in points])

    @classmethod
    def batch_serialize(cls, points):
        """Fill the ``_cbytes`` (compressed) and ``_wire`` (native
        uncompressed) memos of every point in one batch-inversion
        pass.  Points already carrying both memos are skipped; the
        rest amortize a single inversion across the whole flush, so
        later ``to_bytes``/``native.*_wire`` calls are dict lookups."""
        todo = [
            p
            for p in points
            if getattr(p, "_cbytes", None) is None
            or getattr(p, "_wire", None) is None
        ]
        if not todo:
            return
        affs = cls.batch_affine(todo)
        for p, aff in zip(todo, affs):
            try:
                if getattr(p, "_cbytes", None) is None:
                    p._cbytes = cls._encode_affine(aff)
                if getattr(p, "_wire", None) is None:
                    p._wire = cls._wire_affine(aff)
            except AttributeError:  # slot-restricted stand-ins
                pass

    def __eq__(self, other) -> bool:
        return isinstance(other, type(self)) and self.ops["eq"](self.jac, other.jac)

    def __hash__(self) -> int:
        return hash((type(self).__name__, self.affine()))

    @classmethod
    def infinity(cls):
        return cls(cls.ops["INF"])

    @classmethod
    def from_affine(cls, aff):
        pt = cls(cls.ops["from_affine"](aff))
        if aff is not None and not pt.is_on_curve():
            raise ValueError("point not on curve")
        return pt

    def in_subgroup(self) -> bool:
        # Unreduced multiply-by-r (mul_scalar reduces mod r and would be
        # vacuous here).
        nt = _native()
        if nt is not None:
            return self._native_mul_raw(nt, F.R).is_infinity()
        return self.ops["is_inf"](self.ops["mul_raw"](self.jac, F.R))

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self.to_bytes().hex()[:16]}…)"

    def _wire_fields(self):
        return (self.to_bytes(),)

    @classmethod
    def _from_wire(cls, data: bytes):
        return cls.from_bytes(data)


@wire("G1")
class G1(_Point):
    """Point on E(Fq): y² = x³ + 4 (48-byte compressed)."""

    ops = _fq_ops
    b = B1

    def is_on_curve(self) -> bool:
        X, Y, Zc = self.jac
        if Zc == 0:
            return True
        # Y² = X³ + 4·Z⁶
        return (Y * Y - (X**3 + B1 * pow(Zc, 6, F.P))) % F.P == 0

    def _native_mul(self, nt, k: int) -> "G1":
        return nt.g1_unwire(nt.g1_mul(nt.g1_wire(self), k), G1)

    _native_mul_raw = _native_mul

    def _encode(self) -> bytes:
        return self._encode_affine(self.affine())

    @staticmethod
    def _encode_affine(aff) -> bytes:
        if aff is None:
            return bytes([0xC0]) + bytes(47)
        x, y = aff
        buf = bytearray(x.to_bytes(48, "big"))
        buf[0] |= 0x80
        if _is_lex_largest_fq(y):
            buf[0] |= 0x20
        return bytes(buf)

    @staticmethod
    def _wire_affine(aff) -> bytes:
        # native/__init__.py g1_wire: 96-byte x||y, all-zero = infinity
        if aff is None:
            return bytes(96)
        return aff[0].to_bytes(48, "big") + aff[1].to_bytes(48, "big")

    @classmethod
    def from_bytes(cls, data: bytes) -> "G1":
        if len(data) != 48:
            raise ValueError("G1 must be 48 bytes compressed")
        flags = data[0]
        if not flags & 0x80:
            raise ValueError("uncompressed G1 not supported")
        if flags & 0x40:
            if any(data[1:]) or flags != 0xC0:
                raise ValueError("malformed G1 infinity")
            return cls.infinity()
        x = int.from_bytes(bytes([flags & 0x1F]) + data[1:], "big")
        if x >= F.P:
            raise ValueError("G1 x out of range")
        y = F.fq_sqrt((x**3 + B1) % F.P)
        if y is None:
            raise ValueError("G1 x not on curve")
        if bool(flags & 0x20) != _is_lex_largest_fq(y):
            y = F.P - y
        pt = cls.from_affine((x, y))
        if not pt.in_subgroup():
            raise ValueError("G1 point not in subgroup")
        pt._cbytes = bytes(data)  # strictly validated ⇒ canonical
        return pt


@wire("G2")
class G2(_Point):
    """Point on the twist E'(Fq2): y² = x³ + 4(1+u) (96-byte compressed)."""

    ops = _fq2_ops
    b = B2

    def is_on_curve(self) -> bool:
        X, Y, Zc = self.jac
        if Zc == F.FQ2_ZERO:
            return True
        z2 = F.fq2_sq(Zc)
        z6 = F.fq2_mul(F.fq2_sq(z2), z2)
        rhs = F.fq2_add(F.fq2_mul(F.fq2_sq(X), X), F.fq2_mul(B2, z6))
        return F.fq2_sq(Y) == rhs

    def _native_mul(self, nt, k: int) -> "G2":
        return nt.g2_unwire(nt.g2_mul(nt.g2_wire(self), k), G2)

    _native_mul_raw = _native_mul

    def _encode(self) -> bytes:
        return self._encode_affine(self.affine())

    @staticmethod
    def _encode_affine(aff) -> bytes:
        if aff is None:
            return bytes([0xC0]) + bytes(95)
        (x0, x1), y = aff
        buf = bytearray(x1.to_bytes(48, "big") + x0.to_bytes(48, "big"))
        buf[0] |= 0x80
        if _is_lex_largest_fq2(y):
            buf[0] |= 0x20
        return bytes(buf)

    @staticmethod
    def _wire_affine(aff) -> bytes:
        # native/__init__.py g2_wire: 192-byte x.c0||x.c1||y.c0||y.c1
        if aff is None:
            return bytes(192)
        (x0, x1), (y0, y1) = aff
        return (
            x0.to_bytes(48, "big")
            + x1.to_bytes(48, "big")
            + y0.to_bytes(48, "big")
            + y1.to_bytes(48, "big")
        )

    @classmethod
    def from_bytes(cls, data: bytes) -> "G2":
        if len(data) != 96:
            raise ValueError("G2 must be 96 bytes compressed")
        flags = data[0]
        if not flags & 0x80:
            raise ValueError("uncompressed G2 not supported")
        if flags & 0x40:
            if any(data[1:]) or flags != 0xC0:
                raise ValueError("malformed G2 infinity")
            return cls.infinity()
        x1 = int.from_bytes(bytes([flags & 0x1F]) + data[1:48], "big")
        x0 = int.from_bytes(data[48:], "big")
        if x0 >= F.P or x1 >= F.P:
            raise ValueError("G2 x out of range")
        x = (x0, x1)
        rhs = F.fq2_add(F.fq2_mul(F.fq2_sq(x), x), B2)
        y = F.fq2_sqrt(rhs)
        if y is None:
            raise ValueError("G2 x not on curve")
        if bool(flags & 0x20) != _is_lex_largest_fq2(y):
            y = F.fq2_neg(y)
        pt = cls.from_affine((x, y))
        if not pt.in_subgroup():
            raise ValueError("G2 point not in subgroup")
        pt._cbytes = bytes(data)  # strictly validated ⇒ canonical
        return pt


G1_GEN = G1.from_affine((_G1_X, _G1_Y))
G2_GEN = G2.from_affine((_G2_X, _G2_Y))


def g1_multi_exp(points, scalars) -> G1:
    """Σ kᵢ·Pᵢ — Pippenger on the native host path when available,
    naive double-and-add otherwise (the TPU path lives in ops/ec_jax.py)."""
    points = list(points)
    scalars = list(scalars)
    nt = _native()
    if nt is not None and points:
        return nt.g1_unwire(
            nt.g1_msm([nt.g1_wire(p) for p in points], [int(k) % F.R for k in scalars]),
            G1,
        )
    acc = G1.infinity()
    for p, k in zip(points, scalars):
        acc = acc + p * k
    return acc


def g2_multi_exp(points, scalars) -> G2:
    points = list(points)
    scalars = list(scalars)
    nt = _native()
    if nt is not None and points:
        return nt.g2_unwire(
            nt.g2_msm([nt.g2_wire(p) for p in points], [int(k) % F.R for k in scalars]),
            G2,
        )
    acc = G2.infinity()
    for p, k in zip(points, scalars):
        acc = acc + p * k
    return acc
