"""Polynomials over Fr and group commitments — the Shamir layer.

Replaces ``threshold_crypto``'s ``poly`` module (used by the DKG at
``sync_key_gen.rs:164-166``: ``Poly``, ``BivarPoly``, ``BivarCommitment``)
and the Lagrange machinery behind ``combine_signatures`` / ``decrypt``.

Commitments live in G2 (public-key group); the bivariate polynomial is
symmetric, which is what lets DKG participants cross-verify each other's
rows (value at (i, j) equals value at (j, i)).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Sequence, Tuple

from . import fields as F
from .curve import G2, G2_GEN, g2_multi_exp
from ..core.serialize import wire

R = F.R


def _rand_fr(rng) -> int:
    return rng.randrange(R)


# ---------------------------------------------------------------------------
# Lagrange interpolation at zero
# ---------------------------------------------------------------------------


_LAGRANGE_CACHE: dict = {}


def lagrange_coefficients_at_zero(xs: Sequence[int]) -> List[int]:
    """λᵢ = Π_{j≠i} xⱼ/(xⱼ−xᵢ) mod r, for interpolation at x=0.

    ``xs`` must be distinct and nonzero (we use index+1 as evaluation
    points, mirroring the reference's convention).

    Cached by the point set: one co-simulated epoch combines N
    contributions from the *same* lowest-t+1 share subset, and the
    O(k²) Python coefficient computation dominated the combine
    (~80 ms at k=342 vs ~9 ms for the native MSM)."""
    key = tuple(xs)
    cached = _LAGRANGE_CACHE.get(key)
    if cached is not None:
        return list(cached)
    lams = []
    for i, xi in enumerate(xs):
        num, den = 1, 1
        for j, xj in enumerate(xs):
            if i == j:
                continue
            num = num * xj % R
            den = den * (xj - xi) % R
        lams.append(num * pow(den, -1, R) % R)
    if len(_LAGRANGE_CACHE) >= 64:
        _LAGRANGE_CACHE.pop(next(iter(_LAGRANGE_CACHE)))
    _LAGRANGE_CACHE[key] = lams
    return list(lams)


def interpolate_at_zero(points: Sequence[Tuple[int, int]]) -> int:
    """Interpolate scalar shares (x, y) at 0 over Fr."""
    xs = [x for x, _ in points]
    lams = lagrange_coefficients_at_zero(xs)
    return sum(lam * y for lam, (_, y) in zip(lams, points)) % R


# ---------------------------------------------------------------------------
# Univariate polynomials
# ---------------------------------------------------------------------------


@wire("Poly")
@dataclasses.dataclass
class Poly:
    """Univariate polynomial over Fr, coefficient order low→high."""

    coeffs: List[int]

    @classmethod
    def random(cls, degree: int, rng) -> "Poly":
        return cls([_rand_fr(rng) for _ in range(degree + 1)])

    @classmethod
    def constant(cls, c: int) -> "Poly":
        return cls([c % R])

    @property
    def degree(self) -> int:
        return len(self.coeffs) - 1

    def evaluate(self, x: int) -> int:
        acc = 0
        for c in reversed(self.coeffs):
            acc = (acc * x + c) % R
        return acc

    def __add__(self, other: "Poly") -> "Poly":
        n = max(len(self.coeffs), len(other.coeffs))
        a = self.coeffs + [0] * (n - len(self.coeffs))
        b = other.coeffs + [0] * (n - len(other.coeffs))
        return Poly([(x + y) % R for x, y in zip(a, b)])

    def commitment(self) -> "Commitment":
        return Commitment([G2_GEN * c for c in self.coeffs])


@wire("Commitment")
@dataclasses.dataclass
class Commitment:
    """Coefficient-wise G2 commitment of a :class:`Poly`."""

    coeffs: List[G2]

    @property
    def degree(self) -> int:
        return len(self.coeffs) - 1

    def evaluate(self, x: int) -> G2:
        from .curve import _native

        if _native() is None:
            # Horner keeps scalars small on the pure-Python path
            acc = G2.infinity()
            for c in reversed(self.coeffs):
                acc = acc * x + c
            return acc
        # One MSM over [1, x, x², …] beats Horner's per-step scalar mul
        # (a single native Pippenger call vs degree+1 full G2 muls).
        x = x % R
        powers, acc = [], 1
        for _ in self.coeffs:
            powers.append(acc)
            acc = acc * x % R
        return g2_multi_exp(self.coeffs, powers)

    def __add__(self, other: "Commitment") -> "Commitment":
        n = max(len(self.coeffs), len(other.coeffs))
        a = self.coeffs + [G2.infinity()] * (n - len(self.coeffs))
        b = other.coeffs + [G2.infinity()] * (n - len(other.coeffs))
        return Commitment([x + y for x, y in zip(a, b)])

    def __eq__(self, other) -> bool:
        return isinstance(other, Commitment) and all(
            a == b for a, b in zip(self.coeffs, other.coeffs)
        ) and len(self.coeffs) == len(other.coeffs)


# ---------------------------------------------------------------------------
# Symmetric bivariate polynomials (DKG dealing)
# ---------------------------------------------------------------------------


@wire("BivarPoly")
@dataclasses.dataclass
class BivarPoly:
    """Symmetric bivariate polynomial p(x, y) of degree ≤ t in each
    variable; ``coeffs[i][j]`` with coeffs[i][j] == coeffs[j][i].

    Reference: ``threshold_crypto``'s BivarPoly as used by
    ``sync_key_gen.rs:268-299`` for dealing.
    """

    coeffs: List[List[int]]  # (t+1) x (t+1), symmetric

    @classmethod
    def random(cls, degree: int, rng) -> "BivarPoly":
        t = degree
        c = [[0] * (t + 1) for _ in range(t + 1)]
        for i in range(t + 1):
            for j in range(i, t + 1):
                v = _rand_fr(rng)
                c[i][j] = v
                c[j][i] = v
        return cls(c)

    @property
    def degree(self) -> int:
        return len(self.coeffs) - 1

    def evaluate(self, x: int, y: int) -> int:
        acc = 0
        for row in reversed(self.coeffs):
            inner = 0
            for c in reversed(row):
                inner = (inner * y + c) % R
            acc = (acc * x + inner) % R
        return acc

    def row(self, x: int) -> Poly:
        """The univariate polynomial q(y) = p(x, y)."""
        t = self.degree
        out = []
        for j in range(t + 1):
            acc = 0
            for i in reversed(range(t + 1)):
                acc = (acc * x + self.coeffs[i][j]) % R
            out.append(acc)
        return Poly(out)

    def commitment(self) -> "BivarCommitment":
        return BivarCommitment(
            [[G2_GEN * c for c in row] for row in self.coeffs]
        )


@wire("BivarCommitment")
@dataclasses.dataclass
class BivarCommitment:
    """G2 commitment matrix of a symmetric :class:`BivarPoly`."""

    coeffs: List[List[G2]]

    @property
    def degree(self) -> int:
        return len(self.coeffs) - 1

    def evaluate(self, x: int, y: int) -> G2:
        from .curve import _native

        if _native() is None:
            acc = G2.infinity()
            for row in reversed(self.coeffs):
                inner = G2.infinity()
                for c in reversed(row):
                    inner = inner * y + c
                acc = acc * x + inner
            return acc
        # Σᵢⱼ xⁱyʲ·Cᵢⱼ as one flattened MSM.
        x, y = x % R, y % R
        t = self.degree
        xp, acc = [], 1
        for _ in range(t + 1):
            xp.append(acc)
            acc = acc * x % R
        yp, acc = [], 1
        for _ in range(t + 1):
            yp.append(acc)
            acc = acc * y % R
        pts = [c for row in self.coeffs for c in row]
        scalars = [xp[i] * yp[j] % R for i in range(t + 1) for j in range(t + 1)]
        return g2_multi_exp(pts, scalars)

    def row(self, x: int) -> Commitment:
        """Commitment of the row polynomial p(x, ·)."""
        from .curve import _native

        t = self.degree
        if _native() is None:
            out = []
            for j in range(t + 1):
                acc = G2.infinity()
                for i in reversed(range(t + 1)):
                    acc = acc * x + self.coeffs[i][j]
                out.append(acc)
            return Commitment(out)
        x = x % R
        xp, acc = [], 1
        for _ in range(t + 1):
            xp.append(acc)
            acc = acc * x % R
        out = []
        for j in range(t + 1):
            out.append(
                g2_multi_exp([self.coeffs[i][j] for i in range(t + 1)], xp)
            )
        return Commitment(out)

    def is_symmetric(self) -> bool:
        t = self.degree
        if any(len(row) != t + 1 for row in self.coeffs):
            return False
        return all(
            self.coeffs[i][j] == self.coeffs[j][i]
            for i in range(t + 1)
            for j in range(i + 1, t + 1)
        )

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, BivarCommitment)
            and len(self.coeffs) == len(other.coeffs)
            and all(
                len(r1) == len(r2) and all(a == b for a, b in zip(r1, r2))
                for r1, r2 in zip(self.coeffs, other.coeffs)
            )
        )
