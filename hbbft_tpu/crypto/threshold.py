"""Threshold BLS crypto — signatures, coin shares, hybrid encryption.

Replaces the ``threshold_crypto`` crate (``Cargo.toml:29``), the heart
of the reference's security: unique threshold signatures drive the
common coin (``common_coin.rs:142-207``) and threshold decryption makes
HoneyBadger censorship-resistant (``honey_badger.rs:101-444``).

Scheme (re-designed TPU-first — every hot object lives in G1 where the
batched limb kernels operate; G2 appears only in public keys):

- *Signatures / coin shares*: min-sig BLS.  σᵢ = skᵢ·H₁(m) ∈ G1,
  pkᵢ = skᵢ·P₂ ∈ G2.  Verify: e(σᵢ, P₂) == e(H₁(m), pkᵢ).
- *Threshold encryption* (Baek–Zheng style hybrid): U = r·P₁,
  K = SHA-256(r·Y₁) with master key Y₁ = s·P₁ ∈ G1, V = m ⊕ stream(K),
  plus a Schnorr proof-of-knowledge of r replacing the reference's
  W = r·H(U,V) validity element — same plaintext-awareness role
  (``Ciphertext::verify``) without needing hash-to-G2.
- *Decryption shares*: dᵢ = skᵢ·U ∈ G1; verify e(dᵢ, P₂) == e(U, pkᵢ);
  combine by Lagrange in the exponent at x=0 (x-coords are index+1).
- *Batch verification*: k shares verify with ONE product-pairing check
  via deterministic (Fiat–Shamir) random linear combination — the 2k
  pairings collapse to 2, and the Σrᵢ·Pᵢ MSMs are exactly the kernels
  the TPU backend executes (``ops/g1_jax.py``).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from . import fields as F
from .curve import G1, G1_GEN, G2, G2_GEN, g1_multi_exp, g2_multi_exp
from .hashing import DST_ENC, DST_POK, DST_SIG, hash_to_fr, hash_to_g1, sha256, xor_stream
from .pairing import pairing_check
from .poly import Commitment, Poly, lagrange_coefficients_at_zero
from ..core.serialize import dumps, wire

R = F.R


def _rand_fr(rng) -> int:
    k = rng.randrange(R - 1) + 1
    return k


# ---------------------------------------------------------------------------
# Signatures
# ---------------------------------------------------------------------------


@wire("Sig")
@dataclasses.dataclass(frozen=True)
class Signature:
    """A (combined) BLS signature in G1."""

    point: G1

    def parity(self) -> bool:
        """Deterministic unpredictable bit — the common-coin value
        (reference ``Signature::parity``)."""
        return bool(sha256(self.point.to_bytes())[0] & 1)

    def to_bytes(self) -> bytes:
        return self.point.to_bytes()


@wire("SigShare")
@dataclasses.dataclass(frozen=True)
class SignatureShare:
    point: G1

    def to_bytes(self) -> bytes:
        return self.point.to_bytes()


@wire("DecShare")
@dataclasses.dataclass(frozen=True)
class DecryptionShare:
    point: G1

    def to_bytes(self) -> bytes:
        return self.point.to_bytes()


# ---------------------------------------------------------------------------
# Ciphertext
# ---------------------------------------------------------------------------


@wire("Ciphertext")
@dataclasses.dataclass(frozen=True)
class Ciphertext:
    """Hybrid threshold ciphertext (U, V, Schnorr PoK (c, z)).

    ``verify()`` plays the role of the reference's
    ``Ciphertext::verify`` (``honey_badger.rs:371``): it proves the
    encryptor knew the randomness r, giving plaintext-awareness.

    **Deviation from the reference's scheme, and why it is safe.**
    ``threshold_crypto`` uses Baek–Zheng: a third element W = r·H(U, V)
    checked by a pairing.  Here the same validity role is filled by a
    Schnorr proof of knowledge of r for U = r·P₁ whose challenge binds
    the whole ciphertext: c = H(DST_POK ‖ U ‖ H(V) ‖ A), A = a·P₁,
    z = a + c·r.  CCA argument (ROM), mirroring Shoup–Gennaro TDH2:

    1. *Validity ⇒ plaintext awareness*: a verifying (c, z) is a Fiat–
       Shamir Schnorr proof, so the encryptor of any valid ciphertext
       knows r (rewinding extractor); a decryption oracle therefore
       tells the adversary nothing it could not compute itself.
    2. *Non-malleability*: c binds U **and** H(V).  Flipping any bit of
       V (the classic ElGamal XOR mauling) or substituting U changes
       the challenge input, and producing a fresh valid (c, z) for the
       mauled pair is another Schnorr forgery.  Transplanting (c, z)
       between ciphertexts fails the same way.  Re-randomizing
       U' = U + s·P₁ requires z' with z'·P₁ − c'·U' = A' and
       c' = H(U' ‖ H(V) ‖ A') — knowing s but not r leaves z' = z + c'·s
       short by exactly the unknown c'·r adjustment.
    3. *Share consistency*: decryption shares (x_i·U) are individually
       verifiable against the public key shares by a pairing
       (``PublicKeyShare.verify_decryption_share``), the TDH2 rôle of
       the per-share DLEQ proofs — combined with (1)/(2) this gives
       threshold-CCA in the random-oracle model.

    The adversarial cases in (2) are exercised by
    ``tests/test_crypto_threshold.py::TestCiphertextAttacks``.
    """

    u: G1
    v: bytes
    c: int
    z: int

    def verify(self) -> bool:
        if self.u.is_infinity():
            return False
        if not (0 <= self.c < R and 0 <= self.z < R):
            return False
        a = G1_GEN * self.z - self.u * self.c
        c2 = hash_to_fr(
            DST_POK + self.u.to_bytes() + sha256(self.v) + a.to_bytes()
        )
        return c2 == self.c

    def to_bytes(self) -> bytes:
        # memoized: the batching layer keys caches by ciphertext bytes
        # on every queued decryption share (frozen dataclass → side
        # attribute)
        cached = getattr(self, "_bytes", None)
        if cached is None:
            cached = dumps(self)
            object.__setattr__(self, "_bytes", cached)
        return cached


# ---------------------------------------------------------------------------
# Individual keys (used for votes + DKG row encryption)
# ---------------------------------------------------------------------------


@wire("PublicKey")
@dataclasses.dataclass(frozen=True)
class PublicKey:
    """Individual public key; pk1 = sk·P₁ (encryption target),
    pk2 = sk·P₂ (signature verification)."""

    pk1: G1
    pk2: G2

    def verify(self, sig: Signature, msg: bytes) -> bool:
        h = hash_to_g1(msg, DST_SIG)
        return pairing_check([(sig.point, G2_GEN), (-h, self.pk2)])

    def encrypt(self, msg: bytes, rng) -> Ciphertext:
        r = _rand_fr(rng)
        u = G1_GEN * r
        key = sha256(DST_ENC + (self.pk1 * r).to_bytes())
        v = xor_stream(key, msg)
        a_r = _rand_fr(rng)
        a = G1_GEN * a_r
        c = hash_to_fr(DST_POK + u.to_bytes() + sha256(v) + a.to_bytes())
        z = (a_r + c * r) % R
        return Ciphertext(u, v, c, z)

    def to_bytes(self) -> bytes:
        return self.pk1.to_bytes() + self.pk2.to_bytes()


@wire("SecretKey")
@dataclasses.dataclass(frozen=True)
class SecretKey:
    """Individual secret key (vote signing ``votes.rs:45-61``, DKG row
    encryption ``sync_key_gen.rs:294``)."""

    scalar: int

    @classmethod
    def random(cls, rng) -> "SecretKey":
        return cls(_rand_fr(rng))

    def public_key(self) -> PublicKey:
        return PublicKey(G1_GEN * self.scalar, G2_GEN * self.scalar)

    def sign(self, msg: bytes) -> Signature:
        return Signature(hash_to_g1(msg, DST_SIG) * self.scalar)

    def decrypt(self, ct: Ciphertext) -> Optional[bytes]:
        if not ct.verify():
            return None
        key = sha256(DST_ENC + (ct.u * self.scalar).to_bytes())
        return xor_stream(key, ct.v)


# ---------------------------------------------------------------------------
# Threshold keys
# ---------------------------------------------------------------------------


@wire("SecretKeyShare")
@dataclasses.dataclass(frozen=True)
class SecretKeyShare:
    """One node's share of the master secret (poly evaluated at idx+1)."""

    scalar: int

    def sign(self, msg: bytes) -> SignatureShare:
        return SignatureShare(hash_to_g1(msg, DST_SIG) * self.scalar)

    def sign_g1(self, h: G1) -> SignatureShare:
        return SignatureShare(h * self.scalar)

    def decrypt_share(self, ct: Ciphertext) -> Optional[DecryptionShare]:
        if not ct.verify():
            return None
        return DecryptionShare(ct.u * self.scalar)

    def decrypt_share_no_verify(self, ct: Ciphertext) -> DecryptionShare:
        """Reference ``honey_badger.rs:400-403`` — ciphertext was already
        verified when the contribution was accepted."""
        return DecryptionShare(ct.u * self.scalar)

    def decrypt_shares_no_verify_batch(self, cts) -> list:
        """Batch counterpart (interface parity with the mock twin; the
        scalar-muls stay sequential host work here)."""
        return [self.decrypt_share_no_verify(ct) for ct in cts]


@wire("PublicKeyShare")
@dataclasses.dataclass(frozen=True)
class PublicKeyShare:
    point: G2  # skᵢ·P₂

    def verify_signature_share(self, share: SignatureShare, msg: bytes) -> bool:
        h = hash_to_g1(msg, DST_SIG)
        return self.verify_signature_share_g1(share, h)

    def verify_signature_share_g1(self, share: SignatureShare, h: G1) -> bool:
        return pairing_check([(share.point, G2_GEN), (-h, self.point)])

    def verify_decryption_share(self, share: DecryptionShare, ct: Ciphertext) -> bool:
        return pairing_check([(share.point, G2_GEN), (-ct.u, self.point)])

    def to_bytes(self) -> bytes:
        return self.point.to_bytes()


@wire("PublicKeySet")
@dataclasses.dataclass(frozen=True)
class PublicKeySet:
    """Master public key material: G2 coefficient commitment (yields all
    public key shares) + the G1 master key (encryption target).

    Reference ``threshold_crypto::PublicKeySet`` as held by
    ``NetworkInfo`` (``messaging.rs:222-401``).
    """

    commitment: Commitment
    master_g1: G1

    @property
    def threshold(self) -> int:
        return self.commitment.degree

    def public_key(self) -> PublicKey:
        return PublicKey(self.master_g1, self.commitment.evaluate(0))

    def _share_cache(self) -> Dict[int, "PublicKeyShare"]:
        # memoized per index (frozen dataclass → side-table)
        cache = getattr(self, "_pks_cache", None)
        if cache is None:
            cache = {}
            object.__setattr__(self, "_pks_cache", cache)
        return cache

    def public_key_share(self, i: int) -> PublicKeyShare:
        # Commitment evaluation is an MSM; every protocol message
        # verification hits this, so memoize per index.
        cache = self._share_cache()
        if i not in cache:
            cache[i] = PublicKeyShare(self.commitment.evaluate(i + 1))
        return cache[i]

    def precompute_shares(self, n: int) -> None:
        """Fill the share cache for indices 0..n−1 in one pass.

        With the native library this uses the forward-difference range
        evaluation (t+1 seeding MSMs, then t point-additions per
        further index — no scalar muls), ~5× the per-index MSMs at
        n=1024; bit-identical results either way."""
        from .. import native as NT

        cache = self._share_cache()
        missing = [i for i in range(n) if i not in cache]
        if not missing:
            return
        # the range kernel always evaluates the full 1..n span; a few
        # pre-cached entries don't justify losing the fast path
        if NT.available() and n > len(self.commitment.coeffs):
            wires = NT.g2_poly_eval_range(
                [NT.g2_wire(c) for c in self.commitment.coeffs], n, R
            )
            for i in missing:
                cache[i] = PublicKeyShare(NT.g2_unwire(wires[i], G2))
            return
        for i in missing:
            self.public_key_share(i)

    def seed_share_cache_from_scalars(self, scalars) -> None:
        """Co-simulation fast path: fill the share cache from KNOWN
        share scalars — for consistently generated keys (a dealt
        ``SecretKeySet`` or a completed DKG) the commitment evaluation
        satisfies ``commitment.evaluate(i+1) == G2·share_i``, so each
        cached point costs one shared-base comb multiplication instead
        of a (t+1)-point MSM (~300× less group work at N=1024; the
        era-switch's NetworkInfo rebuild was dominated by this).  The
        caller must hold the scalars legitimately (the co-simulation
        deals or co-simulates the DKG centrally); a real node cannot
        take this path — it runs ``precompute_shares`` instead.
        ``scalars``: index → share scalar."""
        from .. import native as NT

        cache = self._share_cache()
        missing = sorted(i for i in scalars if i not in cache)
        if not missing:
            return
        if NT.available():
            import numpy as np

            ks = np.frombuffer(
                b"".join(
                    int(scalars[i] % R).to_bytes(32, "big")
                    for i in missing
                ),
                dtype=np.uint8,
            )
            raw = NT.g2_mul_many_raw(NT.g2_wire(G2_GEN), ks).tobytes()
            for j, i in enumerate(missing):
                cache[i] = PublicKeyShare(
                    NT.g2_unwire(raw[j * 192 : (j + 1) * 192], G2)
                )
            return
        for i in missing:
            cache[i] = PublicKeyShare(G2_GEN * scalars[i])

    # -- combination ------------------------------------------------------

    def combine_signatures(
        self, shares: Dict[int, SignatureShare]
    ) -> Signature:
        """Lagrange-combine > threshold shares; deterministic share-subset
        rule: lowest t+1 indices (bit-identity across CPU/TPU paths)."""
        idxs = sorted(shares)[: self.threshold + 1]
        if len(idxs) <= self.threshold:
            raise ValueError("not enough signature shares")
        xs = [i + 1 for i in idxs]
        lams = lagrange_coefficients_at_zero(xs)
        return Signature(
            g1_multi_exp([shares[i].point for i in idxs], lams)
        )

    def combine_decryption_shares(
        self, shares: Dict[int, DecryptionShare], ct: Ciphertext
    ) -> bytes:
        idxs = sorted(shares)[: self.threshold + 1]
        if len(idxs) <= self.threshold:
            raise ValueError("not enough decryption shares")
        xs = [i + 1 for i in idxs]
        lams = lagrange_coefficients_at_zero(xs)
        s = g1_multi_exp([shares[i].point for i in idxs], lams)
        key = sha256(DST_ENC + s.to_bytes())
        return xor_stream(key, ct.v)

    def combine_decryption_shares_many(
        self,
        rows: Sequence[Dict[int, DecryptionShare]],
        cts: Sequence[Ciphertext],
    ) -> List[bytes]:
        """Batched combine across proposers (the decryption phase of a
        whole co-simulated epoch, ``honey_badger.rs:340`` deduplicated):
        rows sharing one lowest-(t+1) valid-index subset — every
        proposer, in the honest schedule — run as ONE native call over
        the shared Lagrange weight vector (``hb_g1_msm_many``; the r5
        phase profile measured the per-proposer Python combine loop at
        22 s of the 162 s epoch).  Rows with a different subset
        (Byzantine senders knocked their shares out for some proposer)
        fall back to the per-row path.  Bit-identical to mapping
        :meth:`combine_decryption_shares` over the rows."""
        from .. import native as NT

        groups: Dict[Tuple[int, ...], List[int]] = {}
        for i, row in enumerate(rows):
            idxs = tuple(sorted(row)[: self.threshold + 1])
            if len(idxs) <= self.threshold:
                raise ValueError("not enough decryption shares")
            groups.setdefault(idxs, []).append(i)
        out: List[Optional[bytes]] = [None] * len(rows)
        for idxs, members in sorted(groups.items()):
            sample = rows[members[0]][idxs[0]]
            if (
                NT.available()
                and len(members) >= 4
                and isinstance(sample, DecryptionShare)
                and isinstance(sample.point, G1)
            ):
                import numpy as np

                xs = [i + 1 for i in idxs]
                lams = lagrange_coefficients_at_zero(xs)
                kbuf = np.frombuffer(
                    b"".join(int(l % R).to_bytes(32, "big") for l in lams),
                    dtype=np.uint8,
                )
                pts = np.frombuffer(
                    b"".join(
                        NT.g1_wire(rows[i][j].point)
                        for i in members
                        for j in idxs
                    ),
                    dtype=np.uint8,
                )
                raw = NT.g1_msm_many_raw(
                    len(members), len(idxs), pts, kbuf
                ).tobytes()
                for mi, i in enumerate(members):
                    s = NT.g1_unwire(raw[mi * 96 : (mi + 1) * 96], G1)
                    key = sha256(DST_ENC + s.to_bytes())
                    out[i] = xor_stream(key, cts[i].v)
            else:
                for i in members:
                    out[i] = self.combine_decryption_shares(
                        rows[i], cts[i]
                    )
        return out

    def _combine_decryption_points(
        self, rows: Sequence[Dict[int, DecryptionShare]]
    ) -> List[G1]:
        """The combine half of :meth:`combine_decryption_shares_many`
        with the combined G1 points kept (the speculative path still
        needs them for the master-key check before deriving keys).
        Same grouping + native many-MSM dispatch, bit-identical
        points."""
        from .. import native as NT

        groups: Dict[Tuple[int, ...], List[int]] = {}
        for i, row in enumerate(rows):
            idxs = tuple(sorted(row)[: self.threshold + 1])
            if len(idxs) <= self.threshold:
                raise ValueError("not enough decryption shares")
            groups.setdefault(idxs, []).append(i)
        out: List[Optional[G1]] = [None] * len(rows)
        for idxs, members in sorted(groups.items()):
            sample = rows[members[0]][idxs[0]]
            xs = [i + 1 for i in idxs]
            lams = lagrange_coefficients_at_zero(xs)
            if (
                NT.available()
                and len(members) >= 4
                and isinstance(sample, DecryptionShare)
                and isinstance(sample.point, G1)
            ):
                import numpy as np

                kbuf = np.frombuffer(
                    b"".join(int(l % R).to_bytes(32, "big") for l in lams),
                    dtype=np.uint8,
                )
                pts = np.frombuffer(
                    b"".join(
                        NT.g1_wire(rows[i][j].point)
                        for i in members
                        for j in idxs
                    ),
                    dtype=np.uint8,
                )
                raw = NT.g1_msm_many_raw(
                    len(members), len(idxs), pts, kbuf
                ).tobytes()
                for mi, i in enumerate(members):
                    out[i] = NT.g1_unwire(raw[mi * 96 : (mi + 1) * 96], G1)
            else:
                for i in members:
                    out[i] = g1_multi_exp(
                        [rows[i][j].point for j in idxs], lams
                    )
        return out

    def combine_and_check_decryption_shares(
        self, shares: Dict[int, DecryptionShare], ct: Ciphertext
    ) -> Optional[bytes]:
        """Speculative combine-first decryption (arXiv:2407.12172):
        Lagrange-combine the lowest t+1 shares *unverified*, then
        validate the single combined point against the master key with
        one check — the correct combination is s·U, so
        e(s_comb, P₂) == e(U, mpk₂) holds iff every subset share was
        honest (a bad share perturbs the interpolation off the s·U
        ray).  Returns the plaintext, or ``None`` on mismatch so the
        caller can fall back to per-share verification for fault
        attribution.  On the happy path this replaces t+1 two-pairing
        share verifies with one combine (already paid) plus one
        two-pairing check."""
        idxs = sorted(shares)[: self.threshold + 1]
        if len(idxs) <= self.threshold:
            raise ValueError("not enough decryption shares")
        xs = [i + 1 for i in idxs]
        lams = lagrange_coefficients_at_zero(xs)
        s = g1_multi_exp([shares[i].point for i in idxs], lams)
        if not pairing_check(
            [(s, G2_GEN), (-ct.u, self.commitment.evaluate(0))]
        ):
            return None
        key = sha256(DST_ENC + s.to_bytes())
        return xor_stream(key, ct.v)

    def combine_and_check_decryption_shares_many(
        self,
        rows: Sequence[Dict[int, DecryptionShare]],
        cts: Sequence[Ciphertext],
    ) -> List[Optional[bytes]]:
        """Batched speculative combine across proposers: combine every
        row (native many-MSM path), then validate ALL combined points
        with ONE two-pairing RLC check —
        e(Σᵢ rᵢ·sᵢ, P₂) == e(Σᵢ rᵢ·Uᵢ, mpk₂) — valid because every
        proposer's check shares the same G2 side (the master public
        key).  A whole epoch's P proposer checks collapse to two
        P-point G1 MSMs and two pairings.  On aggregate mismatch each
        row is re-checked individually, so exactly the bad rows come
        back ``None``.  Row-wise equal to mapping
        :meth:`combine_and_check_decryption_shares`."""
        if not rows:
            return []
        pts = self._combine_decryption_points(rows)
        mpk2 = self.commitment.evaluate(0)
        rs = _rlc_coeffs(
            b"hbbft_tpu spec combine",
            [p.to_bytes() for p in pts] + [ct.u.to_bytes() for ct in cts],
        )[: len(rows)]
        agg_s = g1_multi_exp(pts, rs)
        agg_u = g1_multi_exp([ct.u for ct in cts], rs)
        def _key(p: G1, ct: Ciphertext) -> bytes:
            return xor_stream(sha256(DST_ENC + p.to_bytes()), ct.v)

        if pairing_check([(agg_s, G2_GEN), (-agg_u, mpk2)]):
            return [_key(p, ct) for p, ct in zip(pts, cts)]
        return [
            _key(p, ct)
            if pairing_check([(p, G2_GEN), (-ct.u, mpk2)])
            else None
            for p, ct in zip(pts, cts)
        ]

    def verify_signature(self, sig: Signature, msg: bytes) -> bool:
        h = hash_to_g1(msg, DST_SIG)
        return pairing_check(
            [(sig.point, G2_GEN), (-h, self.commitment.evaluate(0))]
        )


@wire("SecretKeySet")
@dataclasses.dataclass(frozen=True)
class SecretKeySet:
    """Trusted-dealer secret polynomial (test key dealing — the DKG
    replaces this in production; reference ``messaging.rs:359-400``)."""

    poly: Poly

    @classmethod
    def random(cls, threshold: int, rng) -> "SecretKeySet":
        return cls(Poly.random(threshold, rng))

    @property
    def threshold(self) -> int:
        return self.poly.degree

    def secret_key_share(self, i: int) -> SecretKeyShare:
        return SecretKeyShare(self.poly.evaluate(i + 1))

    def public_keys(self) -> PublicKeySet:
        return PublicKeySet(
            self.poly.commitment(), G1_GEN * self.poly.coeffs[0]
        )


# ---------------------------------------------------------------------------
# Batched verification (host orchestration of the TPU MSM kernels)
# ---------------------------------------------------------------------------


def _rlc_coeffs(context: bytes, items: Sequence[bytes]) -> List[int]:
    """Deterministic 128-bit random-linear-combination coefficients
    (Fiat–Shamir over all inputs) — reproducible across backends."""
    seed = sha256(context + b"".join(items))
    return [
        int.from_bytes(sha256(seed + i.to_bytes(4, "big"))[:16], "big") | 1
        for i in range(len(items))
    ]


def aggregate_by_point(points: Sequence, coeffs: Sequence[int]):
    """Collapse duplicate points by summing their coefficients:
    Σᵢ rᵢ·Pᵢ == Σ_distinct (Σ_{i: Pᵢ=P} rᵢ)·P.

    A batch of one epoch's share verifications has K = N·N obligations
    but only N distinct public keys (``honey_badger.rs:422-444``), so
    this shrinks the expensive G2 MSM from K to ≤N points.  Sums are
    *not* reduced mod r, keeping them ≤ ~128+log₂K bits so the device
    MSM scan stays short (``ops/ec_jax._width``)."""
    agg: Dict[bytes, int] = {}
    first: Dict[bytes, Any] = {}
    for p, c in zip(points, coeffs):
        key = p.to_bytes()
        agg[key] = agg.get(key, 0) + c
        first.setdefault(key, p)
    keys = list(agg)
    return [first[k] for k in keys], [agg[k] for k in keys]


def batch_verify_shares(
    shares: Sequence[G1],
    pks: Sequence[G2],
    base: G1,
    context: bytes = b"",
) -> bool:
    """Check e(shareᵢ, P₂) == e(base, pkᵢ) for all i with one product
    pairing: e(Σrᵢ·shareᵢ, P₂) · e(−base, Σrᵢ·pkᵢ) == 1.

    This is the hot verification path of the whole framework (N² share
    verifies per HoneyBadger epoch, ``honey_badger.rs:422-444``); the
    MSMs are what the TPU backend offloads.
    """
    if not shares:
        return True
    coeffs = _rlc_coeffs(
        context, [s.to_bytes() for s in shares] + [p.to_bytes() for p in pks]
    )[: len(shares)]  # one rᵢ per (shareᵢ, pkᵢ) pair; Fiat–Shamir binds all inputs
    agg_share = g1_multi_exp(shares, coeffs)
    u_pks, u_coeffs = aggregate_by_point(pks, coeffs)
    agg_pk = g2_multi_exp(u_pks, u_coeffs)
    return pairing_check([(agg_share, G2_GEN), (-base, agg_pk)])
