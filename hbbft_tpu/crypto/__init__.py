"""hbbft_tpu.crypto subpackage."""
