"""Insecure hash-based mock crypto — fast protocol-logic testing.

Drop-in interface twins of the real threshold types in
``hbbft_tpu/crypto/threshold.py`` with identical *functional* semantics:

- combining any > t verified shares yields the same deterministic result
  (like Lagrange interpolation does);
- forged or wrong shares fail share verification (so fault attribution
  paths behave exactly as with real BLS);
- threshold encryption round-trips and ``Ciphertext.verify`` rejects
  tampered ciphertexts.

None of the security: every key object carries the group seed.  This
exists so the adversarial protocol sweeps (reference test strategy,
SURVEY §4 — dozens of full network simulations per test file) run in
milliseconds, while the real-BLS path is exercised by dedicated crypto
tests and smaller real-crypto integration runs.  **Never use outside
tests/benchmarks.**
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

from .hashing import sha256, xor_stream
from ..core.serialize import dumps, wire


def _tag_preimage(*parts: bytes) -> bytes:
    out = []
    for p in parts:
        out.append(len(p).to_bytes(4, "big"))
        out.append(p)
    return b"".join(out)


def _tag(*parts: bytes) -> bytes:
    return sha256(_tag_preimage(*parts))


def _idx(i: int) -> bytes:
    return i.to_bytes(8, "big")


_KEY_CACHE: dict = {}  # (seed, nonce) → symmetric key


def _enc_key(seed: bytes, nonce: bytes) -> bytes:
    """The per-ciphertext symmetric key — memoized because a co-simulated
    decryption round derives it once per (group, ciphertext) but calls
    ``decrypt_share_no_verify`` per *(sender, ciphertext)* (N× more)."""
    k = (seed, nonce)
    key = _KEY_CACHE.get(k)
    if key is None:
        if len(_KEY_CACHE) > 1 << 16:
            _KEY_CACHE.clear()
        key = _tag(b"KEY", seed, nonce)
        _KEY_CACHE[k] = key
    return key


@wire("MockSig")
@dataclasses.dataclass(frozen=True)
class MockSignature:
    tag: bytes

    def parity(self) -> bool:
        return bool(self.tag[0] & 1)

    def to_bytes(self) -> bytes:
        return self.tag


@wire("MockSigShare")
@dataclasses.dataclass(frozen=True)
class MockSignatureShare:
    tag: bytes
    combined: bytes  # the group signature this share contributes to

    def to_bytes(self) -> bytes:
        return self.tag + self.combined


@wire("MockDecShare")
@dataclasses.dataclass(frozen=True)
class MockDecryptionShare:
    tag: bytes
    key: bytes  # the symmetric key this share contributes to

    def to_bytes(self) -> bytes:
        return self.tag + self.key


@wire("MockCiphertext")
@dataclasses.dataclass(frozen=True)
class MockCiphertext:
    seed_id: bytes
    nonce: bytes
    v: bytes
    mac: bytes

    def verify(self) -> bool:
        return self.mac == _tag(b"CTMAC", self.seed_id, self.nonce, self.v)

    def to_bytes(self) -> bytes:
        # memoized — the batching layer keys caches by these bytes
        cached = getattr(self, "_bytes", None)
        if cached is None:
            cached = dumps(self)
            object.__setattr__(self, "_bytes", cached)
        return cached


@wire("MockPublicKey")
@dataclasses.dataclass(frozen=True)
class MockPublicKey:
    seed: bytes

    def verify(self, sig: MockSignature, msg: bytes) -> bool:
        return sig.tag == _tag(b"SIG", self.seed, msg)

    def encrypt(self, msg: bytes, rng) -> MockCiphertext:
        nonce = rng.randrange(2**128).to_bytes(16, "big")
        seed_id = _tag(b"SEEDID", self.seed)
        v = xor_stream(_enc_key(self.seed, nonce), msg)
        return MockCiphertext(
            seed_id, nonce, v, _tag(b"CTMAC", seed_id, nonce, v)
        )

    def to_bytes(self) -> bytes:
        return self.seed


@wire("MockSecretKey")
@dataclasses.dataclass(frozen=True)
class MockSecretKey:
    seed: bytes

    @classmethod
    def random(cls, rng) -> "MockSecretKey":
        return cls(rng.randrange(2**256).to_bytes(32, "big"))

    def public_key(self) -> MockPublicKey:
        return MockPublicKey(self.seed)

    def sign(self, msg: bytes) -> MockSignature:
        return MockSignature(_tag(b"SIG", self.seed, msg))

    def decrypt(self, ct: MockCiphertext) -> Optional[bytes]:
        if not ct.verify():
            return None
        return xor_stream(_enc_key(self.seed, ct.nonce), ct.v)


@wire("MockSecretKeyShare")
@dataclasses.dataclass(frozen=True)
class MockSecretKeyShare:
    seed: bytes
    index: int

    def sign(self, msg: bytes) -> MockSignatureShare:
        combined = _tag(b"SIG", self.seed, msg)
        return MockSignatureShare(
            _tag(b"SIGSHARE", self.seed, _idx(self.index), combined), combined
        )

    def decrypt_share(self, ct: MockCiphertext) -> Optional[MockDecryptionShare]:
        if not ct.verify():
            return None
        return self.decrypt_share_no_verify(ct)

    def decrypt_share_no_verify(self, ct: MockCiphertext) -> MockDecryptionShare:
        key = _enc_key(self.seed, ct.nonce)
        return MockDecryptionShare(
            _tag(b"DECSHARE", self.seed, _idx(self.index), key), key
        )

    def decrypt_shares_no_verify_batch(self, cts) -> list:
        """Batch of :meth:`decrypt_share_no_verify` — one batched hash
        call for all tags (the co-simulated decryption phase generates
        t+1 × P shares; the per-call ``_tag`` overhead dominated the
        mock epoch profile).  Preimages go through the same
        ``_tag_preimage`` as :func:`_tag`, so batch- and singly-built
        shares are byte-identical by construction."""
        from .backend import default_backend

        keys = [_enc_key(self.seed, ct.nonce) for ct in cts]
        # _tag_preimage concatenates independent per-part frames, so the
        # loop-invariant prefix hoists without any framing drift risk
        prefix = _tag_preimage(b"DECSHARE", self.seed, _idx(self.index))
        msgs = [prefix + _tag_preimage(k) for k in keys]
        tags = default_backend().sha256_many(msgs)
        return [
            MockDecryptionShare(t, k) for t, k in zip(tags, keys)
        ]


@wire("MockPublicKeyShare")
@dataclasses.dataclass(frozen=True)
class MockPublicKeyShare:
    seed: bytes
    index: int

    def verify_signature_share(self, share: MockSignatureShare, msg: bytes) -> bool:
        combined = _tag(b"SIG", self.seed, msg)
        return share.combined == combined and share.tag == _tag(
            b"SIGSHARE", self.seed, _idx(self.index), combined
        )

    def verify_decryption_share(
        self, share: MockDecryptionShare, ct: MockCiphertext
    ) -> bool:
        key = _enc_key(self.seed, ct.nonce)
        return share.key == key and share.tag == _tag(
            b"DECSHARE", self.seed, _idx(self.index), key
        )

    def to_bytes(self) -> bytes:
        return self.seed + _idx(self.index)


@wire("MockPublicKeySet")
@dataclasses.dataclass(frozen=True)
class MockPublicKeySet:
    seed: bytes
    threshold_: int

    @property
    def threshold(self) -> int:
        return self.threshold_

    def public_key(self) -> MockPublicKey:
        return MockPublicKey(self.seed)

    def public_key_share(self, i: int) -> MockPublicKeyShare:
        return MockPublicKeyShare(self.seed, i)

    def combine_signatures(
        self, shares: Dict[int, MockSignatureShare]
    ) -> MockSignature:
        if len(shares) <= self.threshold_:
            raise ValueError("not enough signature shares")
        # Deterministic, subset-independent — mirrors Lagrange combine.
        first = shares[sorted(shares)[0]]
        return MockSignature(first.combined)

    def combine_decryption_shares(
        self, shares: Dict[int, MockDecryptionShare], ct: MockCiphertext
    ) -> bytes:
        if len(shares) <= self.threshold_:
            raise ValueError("not enough decryption shares")
        first = shares[sorted(shares)[0]]
        return xor_stream(first.key, ct.v)

    def combine_and_check_decryption_shares(
        self, shares: Dict[int, MockDecryptionShare], ct: MockCiphertext
    ) -> Optional[bytes]:
        """Speculative combine-first twin of the real scheme: returns
        the plaintext if the lowest-(t+1) subset combines to a valid
        result, ``None`` on mismatch (caller falls back to per-share
        verification for fault attribution).  Real Lagrange combination
        depends on *every* subset share, so this checks each subset
        member against the group key — a bogus share anywhere in the
        subset fails the combined check exactly as it would perturb
        the real interpolation off the s·U ray."""
        if len(shares) <= self.threshold_:
            raise ValueError("not enough decryption shares")
        idxs = sorted(shares)[: self.threshold_ + 1]
        key = _enc_key(self.seed, ct.nonce)
        for i in idxs:
            share = shares[i]
            if share.key != key or share.tag != _tag(
                b"DECSHARE", self.seed, _idx(i), key
            ):
                return None
        return xor_stream(key, ct.v)

    def combine_and_check_decryption_shares_many(
        self, rows, cts
    ) -> list:
        return [
            self.combine_and_check_decryption_shares(row, ct)
            for row, ct in zip(rows, cts)
        ]

    def verify_signature(self, sig: MockSignature, msg: bytes) -> bool:
        return sig.tag == _tag(b"SIG", self.seed, msg)


@dataclasses.dataclass(frozen=True)
class MockSecretKeySet:
    seed: bytes
    threshold_: int

    @classmethod
    def random(cls, threshold: int, rng) -> "MockSecretKeySet":
        return cls(rng.randrange(2**256).to_bytes(32, "big"), threshold)

    @property
    def threshold(self) -> int:
        return self.threshold_

    def secret_key_share(self, i: int) -> MockSecretKeyShare:
        return MockSecretKeyShare(self.seed, i)

    def public_keys(self) -> MockPublicKeySet:
        return MockPublicKeySet(self.seed, self.threshold_)
