"""Hashing utilities: SHA-256 helpers, hash-to-G1, keyed streams.

Replaces the reference's use of ``ring`` SHA-256 (``broadcast.rs:161``)
and ``threshold_crypto``'s message-hashing (``hash_g2``) — re-designed
so that *all* curve hashing targets G1 (cheap Fq square roots,
``p ≡ 3 mod 4``), which keeps the TPU limb kernels single-field.

``hash_to_g1`` is constant-scheme try-and-increment with cofactor
clearing; domain separation tags keep signatures, encryption and proofs
in disjoint oracle domains.
"""

from __future__ import annotations

import hashlib

from . import fields as F
from .curve import G1

DST_SIG = b"HBBFT_TPU_BLS_SIG_V1_"
DST_ENC = b"HBBFT_TPU_ENC_V1_"
DST_POK = b"HBBFT_TPU_POK_V1_"


def sha256(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def sha512(data: bytes) -> bytes:
    return hashlib.sha512(data).digest()


def hash_to_fq(data: bytes) -> int:
    """512-bit digest reduced mod p (negligible bias: 2^-131)."""
    return int.from_bytes(sha512(data), "big") % F.P


def hash_to_fr(data: bytes) -> int:
    return int.from_bytes(sha512(data), "big") % F.R


def hash_to_g1(msg: bytes, dst: bytes = DST_SIG) -> G1:
    """Deterministic hash onto the G1 subgroup (try-and-increment +
    cofactor clearing).  Expected 2 iterations; bounded at 256."""
    from .. import native as NT

    nt = NT.backend()
    if nt is not None:
        return nt.g1_unwire(nt.hash_to_g1_bytes(msg, dst), G1)
    for ctr in range(256):
        x = hash_to_fq(dst + len(dst).to_bytes(1, "big") + msg + bytes([ctr]))
        y = F.fq_sqrt((x * x % F.P * x + 4) % F.P)
        if y is None:
            continue
        # Canonical sign: take the lexicographically smaller root, then
        # clear the cofactor to land in the r-torsion subgroup.
        if y > F.P - y:
            y = F.P - y
        pt = G1.from_affine((x, y)) * 1  # noop; keep as G1
        pt = G1(G1.ops["mul_raw"](pt.jac, F.H1))
        if not pt.is_infinity():
            return pt
    raise RuntimeError("hash_to_g1 failed (probability ~2^-256)")


def xor_stream(key: bytes, data: bytes) -> bytes:
    """SHA-256-CTR keystream XOR — the symmetric half of the hybrid
    encryption (the reference's threshold_crypto uses the same hash-
    derived-pad construction)."""
    out = bytearray(len(data))
    block = 0
    pos = 0
    while pos < len(data):
        pad = sha256(key + block.to_bytes(8, "big"))
        n = min(32, len(data) - pos)
        for i in range(n):
            out[pos + i] = data[pos + i] ^ pad[i]
        pos += n
        block += 1
    return bytes(out)
