"""SHA-256 Merkle trees with per-leaf inclusion proofs.

Replaces the ``merkle`` crate (afck fork) + ``ring`` digest
(``Cargo.toml:21,27``; tree build ``broadcast.rs:381``, proof generation
``:390-392``, validation ``:556``, re-rooting after reconstruction
``:683-686``).

Design notes:
- Leaf hashes are domain-separated from interior nodes (0x00/0x01
  prefixes) and include the leaf *index*, which subsumes the reference's
  index-byte workaround for duplicate leaves (``broadcast.rs:371-377``)
  without mutating payloads.
- Odd levels duplicate the trailing hash (deterministic, balanced).
- The tree layout is breadth-first arrays — exactly the layout the
  batched TPU SHA-256 kernel (``ops/sha256_jax.py``) consumes, so CPU
  and device builds are structurally identical.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

from .hashing import sha256
from ..core.serialize import wire

_LEAF = b"\x00"
_NODE = b"\x01"


def leaf_hash(index: int, value: bytes) -> bytes:
    return sha256(_LEAF + index.to_bytes(8, "big") + value)


def node_hash(left: bytes, right: bytes) -> bytes:
    return sha256(_NODE + left + right)


@wire("MerkleProof")
@dataclasses.dataclass(frozen=True)
class MerkleProof:
    """Inclusion proof: (value, index, sibling lemma chain, root).

    Plays the role of the reference's ``proof::Proof`` carried in
    Broadcast ``Value``/``Echo`` messages.
    """

    value: bytes
    index: int
    lemma: tuple  # tuple of sibling hashes, leaf level upward
    root_hash: bytes

    def validate(self, n_leaves: int) -> bool:
        """Recompute the root from value+lemma (reference
        ``validate_proof``, ``broadcast.rs:555-575``)."""
        # a deserialized proof can carry arbitrary field types; a
        # non-int index / non-bytes value / non-sequence lemma must
        # fail validation, not raise
        if (
            not isinstance(self.index, int)
            or isinstance(self.index, bool)
            or not isinstance(self.value, bytes)
            or not isinstance(self.lemma, (tuple, list))
            or not isinstance(self.root_hash, bytes)
        ):
            return False
        if not 0 <= self.index < n_leaves:
            return False
        if len(self.lemma) != _tree_depth(n_leaves):
            return False
        h = leaf_hash(self.index, self.value)
        idx = self.index
        for sib in self.lemma:
            if not isinstance(sib, bytes) or len(sib) != 32:
                return False
            if idx & 1:
                h = node_hash(sib, h)
            else:
                h = node_hash(h, sib)
            idx >>= 1
        return h == self.root_hash


def _tree_depth(n_leaves: int) -> int:
    d = 0
    while (1 << d) < n_leaves:
        d += 1
    return d


class MerkleTree:
    """Breadth-first SHA-256 Merkle tree over a list of byte values.

    Uses the C++ native builder (``native/hbbft_native.cpp``) when the
    shared library is available; the pure-Python path below is the
    fallback and the semantics oracle."""

    def __init__(self, values: List[bytes]):
        if not values:
            raise ValueError("empty Merkle tree")
        self.values = list(values)
        from .. import native as _native

        if _native.available():
            self.levels: List[List[bytes]] = _native.merkle_levels(values)
            return
        level = [leaf_hash(i, v) for i, v in enumerate(values)]
        self.levels = [level]
        while len(level) > 1:
            if len(level) & 1:
                level = level + [level[-1]]
                self.levels[-1] = level
            nxt = [
                node_hash(level[i], level[i + 1])
                for i in range(0, len(level), 2)
            ]
            self.levels.append(nxt)
            level = nxt

    @property
    def root_hash(self) -> bytes:
        return self.levels[-1][0]

    def proof(self, index: int) -> MerkleProof:
        if not 0 <= index < len(self.values):
            raise IndexError(index)
        lemma = []
        idx = index
        for level in self.levels[:-1]:
            sib = idx ^ 1
            lemma.append(level[sib] if sib < len(level) else level[idx])
            idx >>= 1
        return MerkleProof(
            self.values[index], index, tuple(lemma), self.root_hash
        )
