"""Canonical state fingerprints for protocol state machines.

``badgermc`` (``analysis/modelcheck.py``) deduplicates explored network
states by hash, so the fingerprint must be *canonical*: two states that
are behaviourally identical must encode to the same bytes even when
they were built along different delivery schedules.  Pickle bytes are
not canonical — the in-memory run shares sub-objects across containers
while a replayed run deserializes every message independently (same
values, different memo graph), and dict/set insertion order varies with
arrival order.  This module walks the values instead:

- primitives are tag + value framed encodings;
- lists/tuples/deques keep their order (it is real state — a queue's
  order is behaviour);
- dict entries and set elements are sorted by their *encoded* bytes
  (insertion order is an artifact of the schedule, and every
  order-sensitive consumer in ``protocols/`` iterates in canonical
  order — see the ``ordered-iter`` rule and the modelcheck regression
  tests);
- ``random.Random`` encodes its ``getstate()`` tuple;
- arbitrary objects encode as qualified type name + their
  ``__getstate__()`` (which ``NetworkInfo`` et al. already use to
  exclude process-local backends), falling back to ``__dict__`` /
  ``__slots__``.

``snapshot()``/``restore()`` are the paired byte-serialization: plain
pickle (protocol 5), suitable for checkpoint/clone of backend-free
state.  Deployments holding a crypto backend go through
``harness.checkpoint`` which re-injects ``ops`` on load.
"""

from __future__ import annotations

import collections
import enum
import hashlib
import pickle
import random
import struct
from typing import Any

_DEPTH_LIMIT = 200


class DigestError(TypeError):
    """State contains a value the canonical walk cannot encode."""


def _frame(tag: bytes, payload: bytes) -> bytes:
    return tag + struct.pack(">Q", len(payload)) + payload


def _encode(obj: Any, depth: int, stack: set, memo: dict) -> bytes:
    if depth > _DEPTH_LIMIT:
        raise DigestError("state nesting exceeds the digest depth limit")
    if obj is None:
        return b"N"
    if obj is True:
        return b"T"
    if obj is False:
        return b"F"
    t = type(obj)
    if t is int:
        mag = obj.to_bytes((obj.bit_length() + 8) // 8, "big", signed=True)
        return _frame(b"i", mag)
    if t is float:
        return b"f" + struct.pack(">d", obj)
    if t is str:
        return _frame(b"s", obj.encode("utf-8"))
    if t is bytes:
        return _frame(b"b", obj)
    if t is bytearray:
        return _frame(b"b", bytes(obj))
    # One walk never mutates state, so a sub-object appearing twice
    # (protocol instances all share one NetworkInfo, queues share
    # message objects) encodes to the same bytes — memoize by id for
    # the duration of this walk.  The memo also keeps every visited
    # object alive, so ids cannot be recycled mid-walk.
    # the address never reaches the encoding — it only keys the
    # per-walk memo, whose hits are byte-identical re-emissions
    oid = id(obj)  # lint: ok(determinism)
    hit = memo.get(oid)
    if hit is not None:
        return hit[0]
    if t in (list, tuple) or t is collections.deque:
        tag = {list: b"l", tuple: b"t"}.get(t, b"q")
        parts = []
        if oid in stack:
            raise DigestError("cyclic state cannot be fingerprinted")
        stack.add(oid)
        try:
            for item in obj:
                parts.append(_encode(item, depth + 1, stack, memo))
        finally:
            stack.discard(oid)
        enc = _frame(tag, b"".join(parts))
        memo[oid] = (enc, obj)
        return enc
    if t is dict:
        if oid in stack:
            raise DigestError("cyclic state cannot be fingerprinted")
        stack.add(oid)
        try:
            entries = sorted(
                _frame(b"k", _encode(k, depth + 1, stack, memo))
                + _encode(v, depth + 1, stack, memo)
                for k, v in obj.items()
            )
        finally:
            stack.discard(oid)
        enc = _frame(b"d", b"".join(entries))
        memo[oid] = (enc, obj)
        return enc
    if t in (set, frozenset):
        elems = sorted(_encode(e, depth + 1, stack, memo) for e in obj)
        enc = _frame(b"e", b"".join(elems))
        memo[oid] = (enc, obj)
        return enc
    if isinstance(obj, enum.Enum):
        # identity is (enum class, member name); the default
        # __getstate__ walk would pull in the class mappingproxy
        qual = f"{t.__module__}.{t.__qualname__}.{obj.name}"
        return _frame(b"m", qual.encode("utf-8"))
    if isinstance(obj, random.Random):
        return _frame(b"r", _encode(obj.getstate(), depth + 1, stack, memo))
    try:
        import numpy as _np
    except Exception:  # pragma: no cover - numpy is in the image
        _np = None
    if _np is not None and isinstance(obj, _np.ndarray):
        head = f"{obj.dtype.str}|{obj.shape}".encode("ascii")
        enc = _frame(b"a", _frame(b"h", head) + obj.tobytes())
        memo[oid] = (enc, obj)
        return enc
    # Generic object: qualified type name + its state.  Python 3.11+
    # gives every object a default __getstate__ (dict, or a
    # (dict, slots) pair); classes with process-local members
    # (NetworkInfo's ops) override it to exclude them — exactly the
    # exclusion a canonical fingerprint wants.
    qual = f"{t.__module__}.{t.__qualname__}"
    getstate = getattr(obj, "__getstate__", None)
    if getstate is not None:
        try:
            state = getstate()
        except Exception as exc:
            raise DigestError(f"{qual}.__getstate__() failed: {exc!r}")
    else:  # pre-3.11 object without __getstate__
        state = getattr(obj, "__dict__", None)
        slots = []
        for klass in t.__mro__:
            s = getattr(klass, "__slots__", ())
            slots.extend((s,) if isinstance(s, str) else s)
        if slots:
            state = (
                state,
                {s: getattr(obj, s) for s in slots if hasattr(obj, s)},
            )
        elif state is None:
            raise DigestError(f"cannot fingerprint stateless {qual} object")
    if oid in stack:
        raise DigestError("cyclic state cannot be fingerprinted")
    stack.add(oid)
    try:
        body = _encode(state, depth + 1, stack, memo)
    finally:
        stack.discard(oid)
    enc = _frame(b"o", _frame(b"n", qual.encode("utf-8")) + body)
    memo[oid] = (enc, obj)
    return enc


def canonical_bytes(obj: Any) -> bytes:
    """The canonical encoding of ``obj`` (mainly for tests; prefer
    :func:`fingerprint` — states are compared by hash)."""
    return _encode(obj, 0, set(), {})


def fingerprint(obj: Any) -> bytes:
    """A 32-byte canonical digest of ``obj``'s state.  Equal for
    behaviourally-equal states regardless of construction order or
    object-graph sharing; different (up to hash collision) otherwise."""
    return hashlib.sha256(_encode(obj, 0, set(), {})).digest()


def state_eq(a: Any, b: Any) -> bool:
    """Structural state equality via canonical fingerprints."""
    return fingerprint(a) == fingerprint(b)


def snapshot(obj: Any) -> bytes:
    """Serialize state for later :func:`restore` (pickle protocol 5;
    backends are excluded by the owning classes' ``__getstate__``)."""
    return pickle.dumps(obj, protocol=5)


def restore(blob: bytes) -> Any:
    """Inverse of :func:`snapshot`.  Restored state is backend-free;
    callers that need a live crypto backend re-inject it via
    ``harness.checkpoint`` / ``crypto.backend.restore_backend``."""
    return pickle.loads(blob)
