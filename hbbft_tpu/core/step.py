"""Message envelopes and the ``Step`` transition result.

TPU-native re-design of the reference's core runtime types
(reference: ``src/messaging.rs:9-183``):

- ``Target`` / ``TargetedMessage`` / ``SourcedMessage`` — the complete
  "communication backend interface" of the framework.  Delivery is the
  embedding application's job (in-memory router, virtual-time simulator,
  or TCP transport).
- ``Step`` — the result of one deterministic state transition:
  ``output`` values, a ``FaultLog`` of observed Byzantine behaviour, and
  outgoing ``messages`` the *caller* must deliver.

Observability: every fault a Step accumulates (``add_fault`` /
``from_fault``) routes through ``FaultLog.append``, which — when a
trace recorder is installed (``hbbft_tpu.obs``) — emits a ``fault``
telemetry event in the stable compact form ``<node!r>:<KIND>`` and
bumps the per-kind fault counter.  Protocol handlers need no extra
instrumentation.

Everything here is plain data: protocol instances stay pure, sans-IO
state machines, which is what lets the TPU backend batch the crypto of
thousands of instances into single fused device launches without
touching protocol logic.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Generic, Iterable, List, Optional, TypeVar

from .fault import Fault, FaultLog

M = TypeVar("M")
M2 = TypeVar("M2")
O = TypeVar("O")


class Target:
    """Message routing target: every node, or one specific node.

    Reference: ``src/messaging.rs:22-42`` (``Target::{All, Node}``).
    """

    __slots__ = ("node",)

    def __init__(self, node: Any = None):
        self.node = node

    @classmethod
    def all(cls) -> "Target":
        return _TARGET_ALL

    @classmethod
    def to(cls, node: Any) -> "Target":
        if node is None:
            raise ValueError("Target.to(None) is invalid; use Target.all()")
        return cls(node)

    @property
    def is_all(self) -> bool:
        return self.node is None

    def message(self, message: M) -> "TargetedMessage":
        return TargetedMessage(self, message)

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Target) and self.node == other.node

    def __hash__(self) -> int:
        return hash(("Target", self.node))

    def __repr__(self) -> str:
        return "Target.all()" if self.is_all else f"Target.to({self.node!r})"


_TARGET_ALL = Target(None)


@dataclasses.dataclass
class TargetedMessage(Generic[M]):
    """A message annotated with its routing target.

    Reference: ``src/messaging.rs:36-52``.
    """

    target: Target
    message: M

    def map(self, fn: Callable[[M], M2]) -> "TargetedMessage[M2]":
        return TargetedMessage(self.target, fn(self.message))


@dataclasses.dataclass
class SourcedMessage(Generic[M]):
    """A message annotated with the node it came from.

    Reference: ``src/messaging.rs:9-20``.
    """

    source: Any
    message: M


class Step(Generic[O, M]):
    """Result of a single call to a ``DistAlgorithm``'s handler.

    The caller **must** deliver ``messages`` and surface ``fault_log``;
    dropping a Step loses protocol messages (the reference enforces this
    with ``#[must_use]``, ``src/messaging.rs:54-66``; here the test
    harness enforces it by construction — handlers feed steps straight
    into the router).
    """

    __slots__ = ("output", "fault_log", "messages")

    def __init__(
        self,
        output: Optional[Iterable[O]] = None,
        fault_log: Optional[FaultLog] = None,
        messages: Optional[Iterable[TargetedMessage[M]]] = None,
    ):
        self.output: List[O] = list(output) if output else []
        self.fault_log: FaultLog = fault_log if fault_log is not None else FaultLog()
        self.messages: List[TargetedMessage[M]] = list(messages) if messages else []

    # -- constructors ------------------------------------------------------

    @classmethod
    def with_output(cls, output: O) -> "Step[O, M]":
        return cls(output=[output])

    @classmethod
    def from_fault(cls, node_id: Any, kind: Any) -> "Step[O, M]":
        return cls(fault_log=FaultLog.init(node_id, kind))

    @classmethod
    def from_fault_log(cls, fault_log: FaultLog) -> "Step[O, M]":
        return cls(fault_log=fault_log)

    @classmethod
    def from_msg(cls, msg: TargetedMessage[M]) -> "Step[O, M]":
        return cls(messages=[msg])

    # -- combinators (reference ``Step::map/extend_with/extend``) ----------

    def map_messages(self, fn: Callable[[M], M2]) -> "Step[O, M2]":
        """Return a new Step with every message payload mapped by ``fn``."""
        step: Step[O, M2] = Step(output=self.output, fault_log=self.fault_log)
        step.messages = [tm.map(fn) for tm in self.messages]
        return step

    def map_output(self, fn: Callable[[O], Any]) -> "Step[Any, M]":
        step: Step[Any, M] = Step(fault_log=self.fault_log, messages=self.messages)
        step.output = [fn(o) for o in self.output]
        return step

    def extend(self, other: "Step[O, M]") -> "Step[O, M]":
        """Merge ``other`` into self (same message type)."""
        self.output.extend(other.output)
        self.fault_log.merge(other.fault_log)
        self.messages.extend(other.messages)
        return self

    def extend_with(
        self, child: "Step[Any, Any]", msg_fn: Callable[[Any], M]
    ) -> List[Any]:
        """Absorb a child algorithm's step, wrapping its messages with
        ``msg_fn`` into our own namespace; returns the child's output for
        the parent to act on.

        Reference: ``src/messaging.rs:107-130`` — this is how every parent
        protocol consumes its children's transitions.
        """
        self.fault_log.merge(child.fault_log)
        self.messages.extend(tm.map(msg_fn) for tm in child.messages)
        return child.output

    def add_fault(self, node_id: Any, kind: Any) -> "Step[O, M]":
        # FaultLog.append carries the debug-log + trace-telemetry hook
        self.fault_log.append(Fault(node_id, kind))
        return self

    def send_all(self, message: M) -> "Step[O, M]":
        self.messages.append(Target.all().message(message))
        return self

    def send_to(self, node: Any, message: M) -> "Step[O, M]":
        self.messages.append(Target.to(node).message(message))
        return self

    def is_empty(self) -> bool:
        return not self.output and not self.messages and self.fault_log.is_empty()

    def __repr__(self) -> str:
        return (
            f"Step(output={self.output!r}, faults={len(self.fault_log)}, "
            f"messages={len(self.messages)})"
        )
