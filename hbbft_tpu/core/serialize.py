"""Canonical deterministic serialization.

The reference uses ``bincode`` (``Cargo.toml:16``) for every signed or
encrypted payload: HoneyBadger contributions (``honey_badger.rs:115``),
votes (``votes.rs:52``), and DKG rows/values (``sync_key_gen.rs:294,344``).
Because votes and DKG messages are *signed over their serialization*,
the codec must be canonical and deterministic across hosts.

This module provides a compact, self-describing, canonical binary codec:

- fixed tag byte per type;
- integers as sign byte + big-endian magnitude with minimal length;
- maps sorted by encoded key bytes (canonical ordering);
- registered dataclasses encode as ``tag || field values`` so protocol
  messages and crypto objects round-trip for transports and benchmarks.

Everything is host-side; device code never sees serialized bytes.
"""

from __future__ import annotations

import dataclasses
import struct
from typing import Any, Callable, Dict, Tuple, Type

_TAG_NONE = b"\x00"
_TAG_FALSE = b"\x01"
_TAG_TRUE = b"\x02"
_TAG_INT_POS = b"\x03"
_TAG_INT_NEG = b"\x04"
_TAG_BYTES = b"\x05"
_TAG_STR = b"\x06"
_TAG_LIST = b"\x07"
_TAG_DICT = b"\x08"
_TAG_OBJ = b"\x09"
_TAG_TUPLE = b"\x0a"


class SerializationError(Exception):
    pass


# Maximum nesting depth accepted by the decoder.  Honest payloads are a
# handful of levels deep; a crafted frame of nested list headers would
# otherwise recurse until the interpreter dies (RecursionError escapes
# the transport's SerializationError drop path and kills the receive
# loop).
_MAX_DECODE_DEPTH = 64


# registry: class -> (name, to_fields, from_fields)
_BY_CLASS: Dict[type, Tuple[str, Callable[[Any], tuple], Callable[..., Any]]] = {}
_BY_NAME: Dict[str, Tuple[type, Callable[..., Any]]] = {}


def wire(name: str):
    """Class decorator registering a type for canonical serialization.

    For dataclasses the fields are used directly; other classes must
    provide ``_wire_fields(self) -> tuple`` and ``_from_wire(cls, *fields)``.
    """

    def deco(cls):
        if dataclasses.is_dataclass(cls):
            field_names = [f.name for f in dataclasses.fields(cls)]

            def to_fields(obj, _names=tuple(field_names)):
                return tuple(getattr(obj, n) for n in _names)

            def from_fields(*vals):
                return cls(*vals)

        else:
            if not hasattr(cls, "_wire_fields") or not hasattr(cls, "_from_wire"):
                raise TypeError(
                    f"{cls.__name__} must be a dataclass or define _wire_fields/_from_wire"
                )

            def to_fields(obj):
                return obj._wire_fields()

            def from_fields(*vals):
                return cls._from_wire(*vals)

        if name in _BY_NAME and _BY_NAME[name][0] is not cls:
            raise SerializationError(
                f"wire tag {name!r} already registered to "
                f"{_BY_NAME[name][0].__name__}"
            )
        if cls in _BY_CLASS and _BY_CLASS[cls][0] != name:
            raise SerializationError(
                f"{cls.__name__} already registered as wire tag "
                f"{_BY_CLASS[cls][0]!r}"
            )
        # registration runs at import time, before any thread spawns —
        # by the time _encode/_decode race, the registry is read-only
        _BY_CLASS[cls] = (name, to_fields, from_fields)  # lint: ok(thread-shared-state)
        _BY_NAME[name] = (cls, from_fields)
        return cls

    return deco


def _enc_len(n: int) -> bytes:
    if n < 0xFF:
        return bytes([n])
    return b"\xff" + struct.pack(">Q", n)


def _dec_len(buf: bytes, pos: int) -> Tuple[int, int]:
    b0 = buf[pos]
    if b0 < 0xFF:
        return b0, pos + 1
    return struct.unpack_from(">Q", buf, pos + 1)[0], pos + 9


def _encode(obj: Any, out: list) -> None:
    if obj is None:
        out.append(_TAG_NONE)
    elif obj is True:
        out.append(_TAG_TRUE)
    elif obj is False:
        out.append(_TAG_FALSE)
    elif isinstance(obj, int):
        if obj >= 0:
            mag = obj.to_bytes((obj.bit_length() + 7) // 8 or 1, "big")
            out.append(_TAG_INT_POS + _enc_len(len(mag)) + mag)
        else:
            m = -obj
            mag = m.to_bytes((m.bit_length() + 7) // 8 or 1, "big")
            out.append(_TAG_INT_NEG + _enc_len(len(mag)) + mag)
    elif isinstance(obj, (bytes, bytearray, memoryview)):
        b = bytes(obj)
        out.append(_TAG_BYTES + _enc_len(len(b)) + b)
    elif isinstance(obj, str):
        b = obj.encode("utf-8")
        out.append(_TAG_STR + _enc_len(len(b)) + b)
    elif isinstance(obj, (list, tuple)):
        tag = _TAG_LIST if isinstance(obj, list) else _TAG_TUPLE
        out.append(tag + _enc_len(len(obj)))
        for item in obj:
            _encode(item, out)
    elif isinstance(obj, dict):
        items = []
        for k, v in obj.items():
            items.append((dumps(k), v))
        items.sort(key=lambda kv: kv[0])
        out.append(_TAG_DICT + _enc_len(len(items)))
        for kb, v in items:
            out.append(kb)
            _encode(v, out)
    else:
        reg = _BY_CLASS.get(type(obj))
        if reg is None:
            raise SerializationError(f"unserializable type: {type(obj).__name__}")
        name, to_fields, _ = reg
        nb = name.encode("ascii")
        fields = to_fields(obj)
        out.append(_TAG_OBJ + _enc_len(len(nb)) + nb + _enc_len(len(fields)))
        for f in fields:
            _encode(f, out)


def dumps(obj: Any) -> bytes:
    """Serialize ``obj`` to canonical bytes (deterministic: equal objects
    always yield equal bytes — safe to sign)."""
    out: list = []
    _encode(obj, out)
    return b"".join(out)


def _decode(buf: bytes, pos: int, depth: int = 0) -> Tuple[Any, int]:
    if depth > _MAX_DECODE_DEPTH:
        raise SerializationError("nesting too deep")
    tag = buf[pos : pos + 1]
    pos += 1
    if tag == _TAG_NONE:
        return None, pos
    if tag == _TAG_TRUE:
        return True, pos
    if tag == _TAG_FALSE:
        return False, pos
    if tag in (_TAG_INT_POS, _TAG_INT_NEG):
        n, pos = _dec_len(buf, pos)
        mag = int.from_bytes(buf[pos : pos + n], "big")
        return (mag if tag == _TAG_INT_POS else -mag), pos + n
    if tag == _TAG_BYTES:
        n, pos = _dec_len(buf, pos)
        return bytes(buf[pos : pos + n]), pos + n
    if tag == _TAG_STR:
        n, pos = _dec_len(buf, pos)
        return buf[pos : pos + n].decode("utf-8"), pos + n
    if tag in (_TAG_LIST, _TAG_TUPLE):
        n, pos = _dec_len(buf, pos)
        items = []
        for _ in range(n):
            item, pos = _decode(buf, pos, depth + 1)
            items.append(item)
        return (items if tag == _TAG_LIST else tuple(items)), pos
    if tag == _TAG_DICT:
        n, pos = _dec_len(buf, pos)
        d = {}
        for _ in range(n):
            k, pos = _decode(buf, pos, depth + 1)
            v, pos = _decode(buf, pos, depth + 1)
            d[k] = v
        return d, pos
    if tag == _TAG_OBJ:
        n, pos = _dec_len(buf, pos)
        name = buf[pos : pos + n].decode("ascii")
        pos += n
        nf, pos = _dec_len(buf, pos)
        reg = _BY_NAME.get(name)
        if reg is None:
            raise SerializationError(f"unknown wire tag {name!r}")
        _, from_fields = reg
        fields = []
        for _ in range(nf):
            f, pos = _decode(buf, pos, depth + 1)
            fields.append(f)
        return from_fields(*fields), pos
    raise SerializationError(f"bad tag byte {tag!r} at {pos - 1}")


def loads(buf: bytes) -> Any:
    """Decode canonical bytes.  Raises :class:`SerializationError` on ANY
    malformed input — truncation (``IndexError``/``struct.error``), bad
    UTF-8/ASCII, a wrong-arity ``_TAG_OBJ`` frame (``TypeError`` from the
    constructor), a constructor rejecting a field value, or excessive
    nesting.  Transports rely on this: :mod:`..transport.tcp` drops
    frames only on ``SerializationError``; any other exception type
    escaping here would kill the receive loop."""
    try:
        obj, pos = _decode(buf, 0)
    except SerializationError:
        raise
    except Exception as exc:
        raise SerializationError(
            f"malformed input ({type(exc).__name__}: {exc})"
        ) from exc
    if pos != len(buf):
        raise SerializationError(f"trailing bytes after position {pos}")
    return obj
