"""Readable hex helpers for logs (reference ``src/fmt.rs``)."""

from __future__ import annotations

from typing import Iterable


def hex_bytes(data: bytes, max_len: int = 6) -> str:
    """Truncated hex rendering: full if short, ``aabbcc..ddee`` otherwise
    (reference ``fmt.rs:5-24``)."""
    if len(data) <= max_len:
        return data.hex()
    head = data[: max_len - 2].hex()
    tail = data[-2:].hex()
    return f"{head}..{tail}"


def hex_list(items: Iterable[bytes]) -> str:
    return "[" + ", ".join(hex_bytes(b) for b in items) + "]"
