"""The universal distributed-algorithm interface.

Reference: ``src/messaging.rs:186-218`` (``DistAlgorithm`` trait) and
``src/lib.rs:140-155`` (blanket trait aliases).

Every protocol in the framework — Broadcast, CommonCoin, Agreement,
CommonSubset, HoneyBadger, DynamicHoneyBadger, QueueingHoneyBadger — is
a deterministic, single-threaded state machine implementing this
interface.  It owns no threads, sockets or clocks: the caller feeds it
inputs and sourced messages, and it returns a :class:`~hbbft_tpu.core.step.Step`
whose messages the caller must deliver.

This sans-IO design is deliberately preserved from the reference because
it is what makes (a) adversarial in-process network simulation possible
without a cluster and (b) TPU co-simulation of thousands of instances
possible — the state machines are pure, so their crypto workload can be
collected and flushed to the device in fused batches.
"""

from __future__ import annotations

import abc
from typing import Any, Generic, Hashable, TypeVar

from .step import Step

NodeId = TypeVar("NodeId", bound=Hashable)
Input = TypeVar("Input")
Output = TypeVar("Output")
Message = TypeVar("Message")


class DistAlgorithm(abc.ABC, Generic[NodeId, Input, Output, Message]):
    """A distributed algorithm that defines a message flow.

    Associated types of the reference trait map to the generic
    parameters ``NodeId / Input / Output / Message``; errors are raised
    as exceptions (subclasses of :class:`HbbftError`).
    """

    @abc.abstractmethod
    def handle_input(self, input: Input) -> Step[Output, Message]:
        """Handle user input and return the resulting step.

        (Reference ``DistAlgorithm::input``; renamed because ``input`` is
        a Python builtin.)
        """

    @abc.abstractmethod
    def handle_message(self, sender_id: NodeId, message: Message) -> Step[Output, Message]:
        """Handle a message received from ``sender_id``."""

    @abc.abstractmethod
    def terminated(self) -> bool:
        """Whether the algorithm has terminated (no further input/messages)."""

    @abc.abstractmethod
    def our_id(self) -> NodeId:
        """This node's own identifier."""

    # -- canonical state serialization ----------------------------------
    #
    # Every protocol instance is a pure, sans-IO state machine, so its
    # entire behaviour is a function of its attribute state.  These
    # three hooks make that state first-class: ``state_digest`` is the
    # canonical fingerprint badgermc's state-space dedup and the
    # harness's structural-equality checks key on; ``snapshot``/
    # ``restore`` round-trip the state through bytes (crypto backends
    # are excluded by ``__getstate__`` on the owning classes and are
    # re-injected by ``harness.checkpoint`` where needed).

    def state_digest(self) -> bytes:
        """A 32-byte canonical digest of this instance's protocol
        state — equal for behaviourally-equal states regardless of how
        the state was reached or how its object graph is shared."""
        from .digest import fingerprint

        return fingerprint(self)

    def snapshot(self) -> bytes:
        """Serialize this instance's state for :meth:`restore`."""
        from .digest import snapshot

        return snapshot(self)

    @staticmethod
    def restore(blob: bytes) -> "DistAlgorithm":
        """Rebuild an instance from :meth:`snapshot` bytes."""
        from .digest import restore

        return restore(blob)


class HbbftError(Exception):
    """Base class for protocol errors (unrecoverable local conditions —
    Byzantine *remote* behaviour is reported via FaultLog, never raised)."""


class UnknownSenderError(HbbftError):
    pass


class CryptoError(HbbftError):
    pass
