"""Shared per-node network and crypto context.

Reference: ``NetworkInfo`` (``src/messaging.rs:220-401``) — the object
every protocol instance holds (via an immutable shared reference) that
answers "who are the validators, what is f, and which keys do we hold".

This is the seam where the crypto backend is injected (SURVEY §2.1):
every sign/verify/combine/encrypt call in every protocol goes through
values handed out here, and the ``ops`` attribute carries the
batched-operations backend (CPU reference or TPU kernels).
"""

from __future__ import annotations

import hashlib
import random
from typing import Any, Dict, Generic, List, Optional, TypeVar

from ..crypto import mock as M
from ..crypto import threshold as T
from ..crypto.backend import default_backend

N = TypeVar("N")


class NetworkInfo(Generic[N]):
    """Immutable network/crypto context shared by all protocol instances
    of one node."""

    def __init__(
        self,
        our_id: N,
        secret_key_share: Any,
        secret_key: Any,
        public_key_set: Any,
        public_keys: Dict[N, Any],
        ops: Any = None,
    ):
        if not public_keys:
            raise ValueError("validator set must be non-empty")
        self._our_id = our_id
        self._secret_key_share = secret_key_share
        self._secret_key = secret_key
        self._public_key_set = public_key_set
        self._public_keys = dict(public_keys)
        self._all_ids: List[N] = sorted(public_keys)
        self._node_indices: Dict[N, int] = {
            nid: i for i, nid in enumerate(self._all_ids)
        }
        self._is_validator = our_id in self._node_indices
        self._public_key_shares: Dict[N, Any] = {
            nid: public_key_set.public_key_share(i)
            for nid, i in self._node_indices.items()
        }
        self.ops = ops if ops is not None else default_backend()

    # -- identity ----------------------------------------------------------

    @property
    def our_id(self) -> N:
        return self._our_id

    @property
    def our_index(self) -> Optional[int]:
        return self._node_indices.get(self._our_id)

    @property
    def is_validator(self) -> bool:
        """Reference ``messaging.rs:348`` — non-validators (observers)
        handle all messages but send nothing."""
        return self._is_validator

    # -- topology ----------------------------------------------------------

    @property
    def all_ids(self) -> List[N]:
        return self._all_ids

    @property
    def num_nodes(self) -> int:
        return len(self._all_ids)

    @property
    def num_faulty(self) -> int:
        """f = ⌊(N−1)/3⌋ (reference ``messaging.rs:258``)."""
        return (len(self._all_ids) - 1) // 3

    @property
    def num_correct(self) -> int:
        """N − f (reference ``messaging.rs:292-294``)."""
        return len(self._all_ids) - self.num_faulty

    def node_index(self, nid: N) -> Optional[int]:
        return self._node_indices.get(nid)

    def is_node_validator(self, nid: N) -> bool:
        return nid in self._node_indices

    # -- keys --------------------------------------------------------------

    @property
    def secret_key_share(self) -> Any:
        return self._secret_key_share

    @property
    def secret_key(self) -> Any:
        return self._secret_key

    @property
    def public_key_set(self) -> Any:
        return self._public_key_set

    def public_key_share(self, nid: N) -> Any:
        return self._public_key_shares.get(nid)

    def public_key(self, nid: N) -> Any:
        return self._public_keys.get(nid)

    @property
    def public_key_map(self) -> Dict[N, Any]:
        return dict(self._public_keys)

    def invocation_id(self) -> bytes:
        """Unique id of this protocol invocation = master public key bytes
        (reference ``messaging.rs:342-344``); bound into coin nonces."""
        return self._public_key_set.public_key().to_bytes()

    def default_rng(self, label: str = "") -> random.Random:
        """A deterministic per-node RNG — the replacement for ambient
        ``random.Random()`` defaults in the protocol layer (badgerlint
        ``determinism`` rule).

        RFC6979-style derivation: the seed hashes the invocation id,
        our node id, a per-consumer ``label``, and — when we hold one —
        our individual secret key.  Two runs of the same node over the
        same network produce the identical stream (replayable,
        co-simulation-stable), while the stream stays unpredictable to
        other parties because the secret key is folded in.  Observers
        (no secret key) still get a deterministic stream; they never
        use it for anything secrecy-bearing (they propose nothing).
        Callers needing fresh OS entropy instead (e.g. first-node key
        generation) pass an explicit rng."""
        h = hashlib.sha256()
        h.update(b"hbbft_tpu/default_rng/v1|")
        h.update(self.invocation_id())
        h.update(b"|" + repr(self._our_id).encode())
        h.update(b"|" + label.encode())
        if self._secret_key is not None:
            h.update(b"|sk|" + repr(self._secret_key).encode())
        if self._secret_key_share is not None:
            h.update(b"|sks|" + repr(self._secret_key_share).encode())
        return random.Random(int.from_bytes(h.digest(), "big"))

    # -- test key dealing --------------------------------------------------

    @staticmethod
    def generate_map(
        ids, rng, mock: bool = False, ops: Any = None
    ) -> Dict[N, "NetworkInfo[N]"]:
        """Deal threshold + individual keys for all nodes centrally
        (reference ``messaging.rs:359-400``; testing/benchmarks only —
        production uses the dealerless DKG in
        ``hbbft_tpu/protocols/sync_key_gen.py``).

        With ``mock=True`` the insecure fast mock crypto is dealt instead
        (protocol-logic tests)."""
        ids = sorted(ids)
        num_faulty = (len(ids) - 1) // 3
        if mock:
            sk_set = M.MockSecretKeySet.random(num_faulty, rng)
            sec_keys = {nid: M.MockSecretKey.random(rng) for nid in ids}
        else:
            sk_set = T.SecretKeySet.random(num_faulty, rng)
            sec_keys = {nid: T.SecretKey.random(rng) for nid in ids}
        pk_set = sk_set.public_keys()
        key_shares = [sk_set.secret_key_share(i) for i in range(len(ids))]
        if hasattr(pk_set, "seed_share_cache_from_scalars"):
            # the dealer holds every share scalar: one shared-base
            # comb pass fills the cache every NetworkInfo below hits
            # (identical points to evaluating the commitment)
            pk_set.seed_share_cache_from_scalars(
                {i: ks.scalar for i, ks in enumerate(key_shares)}
            )
        elif hasattr(pk_set, "precompute_shares"):
            pk_set.precompute_shares(len(ids))
        pub_keys = {nid: sk.public_key() for nid, sk in sec_keys.items()}
        return {
            nid: NetworkInfo(
                nid,
                key_shares[i],
                sec_keys[nid],
                pk_set,
                pub_keys,
                ops=ops,
            )
            for i, nid in enumerate(ids)
        }

    def observer_view(self, observer_id: N, secret_key: Any = None) -> "NetworkInfo[N]":
        """A non-validator view of the same network (observers verify
        everything but hold no key share; reference test harness
        ``tests/network/mod.rs:402-420``)."""
        return NetworkInfo(
            observer_id,
            None,
            secret_key,
            self._public_key_set,
            self._public_keys,
            ops=self.ops,
        )

    # -- checkpointing -----------------------------------------------------
    # The ops backend is a process-local resource (it may hold compiled
    # device executables); snapshots carry only the plain-data state and
    # the backend is re-injected on restore (harness/checkpoint.py).

    def __getstate__(self):
        state = dict(self.__dict__)
        state.pop("ops", None)
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        from ..crypto.backend import restore_backend

        self.ops = restore_backend()

    def __repr__(self) -> str:
        return (
            f"NetworkInfo(our_id={self._our_id!r}, n={self.num_nodes}, "
            f"f={self.num_faulty}, validator={self.is_validator})"
        )
