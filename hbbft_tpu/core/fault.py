"""Byzantine-fault evidence log.

Every protocol handler attributes observed protocol violations to the
offending node with a typed reason and returns them in its ``Step``;
fault logs propagate up through the protocol stack unchanged, so the
embedding application always learns *who* misbehaved and *how*.

Reference: ``src/fault_log.rs`` (17-variant ``FaultKind``, ``Fault``,
``FaultLog`` with append/extend/merge semantics).
"""

from __future__ import annotations

import dataclasses
import enum
import logging
from typing import Any, Iterator, List

from ..obs import recorder as _obs

# The framework's observability channel (reference: `log` crate macros
# throughout, enabled via RUST_LOG=hbbft=debug — here: configure
# ``logging.getLogger("hbbft_tpu")`` with a handler + DEBUG level).
# Every attributed Byzantine fault is logged as it is recorded; DEBUG
# level keeps adversarial test sweeps (thousands of intended faults)
# quiet by default.
log = logging.getLogger("hbbft_tpu")


class FaultKind(enum.Enum):
    """Typed reasons a node can be flagged as faulty.

    Mirrors the reference's fault taxonomy (``src/fault_log.rs:10-49``)
    so fault attribution is feature-complete; names are framework-local.
    """

    # Threshold decryption (HoneyBadger)
    UNVERIFIED_DECRYPTION_SHARE_SENDER = "sent a decryption share while we have no ciphertext to check it against"
    INVALID_DECRYPTION_SHARE = "sent an invalid threshold-decryption share"
    INVALID_CIPHERTEXT = "proposed an invalid ciphertext"
    SHARE_DECRYPTION_FAILED = "contribution could not be decrypted from combined shares"
    BATCH_DESERIALIZATION_FAILED = "batch contribution failed to deserialize"
    # Common coin
    UNVERIFIED_SIGNATURE_SHARE_SENDER = "sent a signature share before we could verify it"
    INVALID_SIGNATURE_SHARE = "sent an invalid threshold-signature share"
    # Broadcast
    INVALID_PROOF = "sent an Echo or Value with an invalid Merkle proof"
    RECEIVED_VALUE_FROM_NON_PROPOSER = "sent a Value although not the proposer"
    MULTIPLE_VALUES = "sent more than one Value"
    MULTIPLE_ECHOS = "sent more than one Echo"
    MULTIPLE_READYS = "sent more than one Ready"
    BROADCAST_DECODING_FAILED = "broadcast value could not be reconstructed"
    # Agreement
    DUPLICATE_BVAL = "sent a duplicate BVal"
    DUPLICATE_AUX = "sent a duplicate Aux"
    DUPLICATE_CONF = "sent a duplicate Conf"
    DUPLICATE_TERM = "sent a duplicate Term"
    AGREEMENT_EPOCH_BEHIND = "sent an Agreement message for an expired epoch"
    # Common subset
    UNEXPECTED_PROPOSER = "referred to an unknown proposer"
    # Dynamic honey badger / DKG
    INVALID_VOTE_SIGNATURE = "sent a vote with an invalid signature"
    INVALID_KEY_GEN_MESSAGE_SIGNATURE = "sent a key-gen message with an invalid signature"
    INVALID_PART = "committed an invalid DKG Part"
    INVALID_ACK = "committed an invalid DKG Ack"
    MULTIPLE_PARTS = "committed more than one DKG Part"
    UNEXPECTED_KEY_GEN_MESSAGE = "committed an unexpected key-gen message"
    KEY_GEN_MESSAGE_SPAM = "exceeded the key-gen message cap"
    # Generic protocol violations
    INVALID_MESSAGE = "sent a malformed or undecodable message"
    EPOCH_OUT_OF_RANGE = "sent a message for an epoch out of the accepted window"
    INVALID_SNAPSHOT = "served a forged or malformed state-transfer snapshot"

    def __repr__(self) -> str:  # keep logs compact
        return f"FaultKind.{self.name}"


@dataclasses.dataclass(frozen=True)
class Fault:
    """One attributed protocol violation (reference ``fault_log.rs:51-64``)."""

    node_id: Any
    kind: FaultKind

    def compact(self) -> str:
        """THE stable compact form — ``<node_id!r>:<KIND_NAME>`` — used
        by ``__repr__``, the debug log and the ``fault`` trace event,
        so fault telemetry is greppable and byte-stable across runs."""
        return f"{self.node_id!r}:{self.kind.name}"

    def __repr__(self) -> str:
        return f"Fault({self.compact()})"


class FaultLog:
    """Append-only list of :class:`Fault` (reference ``fault_log.rs:66-108``)."""

    __slots__ = ("_faults",)

    def __init__(self, faults: List[Fault] | None = None):
        self._faults: List[Fault] = list(faults) if faults else []

    @classmethod
    def init(cls, node_id: Any, kind: FaultKind) -> "FaultLog":
        # routed through append so every fault creation point shares
        # the same debug-log + trace-telemetry path
        fl = cls()
        fl.append(Fault(node_id, kind))
        return fl

    def append(self, fault: Fault) -> None:
        if log.isEnabledFor(logging.DEBUG):
            log.debug("fault: %s (%s)", fault.compact(), fault.kind.value)
        rec = _obs.ACTIVE
        if rec is not None:
            rec.event(
                "fault",
                fault=fault.compact(),
                node=fault.node_id,
                kind=fault.kind.name,
            )
            rec.count(f"fault.{fault.kind.name}")
        self._faults.append(fault)

    def add(self, node_id: Any, kind: FaultKind) -> None:
        self.append(Fault(node_id, kind))

    def merge(self, other: "FaultLog") -> None:
        """Drain ``other`` into self (reference ``merge_into``)."""
        self._faults.extend(other._faults)

    def is_empty(self) -> bool:
        return not self._faults

    def __len__(self) -> int:
        return len(self._faults)

    def __iter__(self) -> Iterator[Fault]:
        return iter(self._faults)

    def __repr__(self) -> str:
        return f"FaultLog({self._faults!r})"
