"""hbbft_tpu.core subpackage."""
