// BLS12-381 host-native arithmetic for hbbft_tpu.
//
// Native host path for the reference's `pairing` + `threshold_crypto`
// crates (SURVEY.md §2.4): G1/G2 scalar multiplication, Pippenger
// multi-scalar multiplication, the optimal ate pairing, product-pairing
// checks, and hash-to-G1 — the operations behind every signature-share
// sign/verify/combine (common_coin.rs:142-207), decryption-share
// verify/combine (honey_badger.rs:422-444, :340) and DKG value check
// (sync_key_gen.rs:449).
//
// Semantics are identical to the pure-Python oracle in
// hbbft_tpu/crypto/{fields,curve,pairing,hashing}.py: same tower
// (Fq2 = Fq[u]/(u²+1), Fq6 = Fq2[v]/(v³-ξ), ξ=1+u, Fq12 = Fq6[w]/(w²-v)),
// same final-exponentiation decomposition (pairing value = e(P,Q)³),
// same try-and-increment hash-to-G1.  The Miller loop here runs T in
// Jacobian coordinates with polynomial line coefficients (the Python
// oracle uses affine T); each line differs from the affine one only by
// a factor in Fq2*, which the final exponentiation kills, so pairing
// outputs are byte-identical.  tests/test_native_bls.py enforces this.
//
// Wire formats (all big-endian):
//   Fq element   : 48 bytes
//   G1 affine    : 96 bytes (x||y); all-zero = infinity
//   G2 affine    : 192 bytes (x.c0||x.c1||y.c0||y.c1); all-zero = infinity
//   scalar       : 32 bytes
//   Fq12         : 576 bytes (c0.c0.c0, c0.c0.c1, c0.c1.c0, ... row-major
//                  over the Python tuple nesting)

#include <cstdint>
#include <cstring>
#include <vector>

namespace bls {

// ---------------------------------------------------------------------------
// Fp: 381-bit base field, 6x64-bit limbs, Montgomery form (R = 2^384)
// ---------------------------------------------------------------------------

typedef unsigned __int128 u128;

struct Fp {
  uint64_t l[6];
};

static const Fp MOD = {{0xb9feffffffffaaabULL, 0x1eabfffeb153ffffULL,
                        0x6730d2a0f6b0f624ULL, 0x64774b84f38512bfULL,
                        0x4b1ba7b6434bacd7ULL, 0x1a0111ea397fe69aULL}};
static const Fp R2 = {{0xf4df1f341c341746ULL, 0x0a76e6a609d104f1ULL,
                       0x8de5476c4c95b6d5ULL, 0x67eb88a9939d83c0ULL,
                       0x9a793e85b519952dULL, 0x11988fe592cae3aaULL}};
static const uint64_t PINV = 0x89f3fffcfffcfffdULL;
static const Fp FP_ONE = {{0x760900000002fffdULL, 0xebf4000bc40c0002ULL,
                           0x5f48985753c758baULL, 0x77ce585370525745ULL,
                           0x5c071a97a256ec6dULL, 0x15f65ec3fa80e493ULL}};
static const Fp FP_ZERO = {{0, 0, 0, 0, 0, 0}};

// Exponents (plain integers, little-endian limbs)
static const uint64_t EXP_PM2[6] = {0xb9feffffffffaaa9ULL, 0x1eabfffeb153ffffULL,
                                    0x6730d2a0f6b0f624ULL, 0x64774b84f38512bfULL,
                                    0x4b1ba7b6434bacd7ULL, 0x1a0111ea397fe69aULL};
static const uint64_t EXP_SQRT[6] = {0xee7fbfffffffeaabULL, 0x07aaffffac54ffffULL,
                                     0xd9cc34a83dac3d89ULL, 0xd91dd2e13ce144afULL,
                                     0x92c6e9ed90d2eb35ULL, 0x0680447a8e5ff9a6ULL};
static const uint64_t EXP_FROB16[6] = {0x49aa7ffffffff1c7ULL, 0x051caaaa72e35555ULL,
                                       0xe688231ad3c82906ULL, 0xe613e1eb7deb831fULL,
                                       0x0c849bf3b5e1f223ULL, 0x045582fc5eeaa66fULL};
static const uint64_t EXP_FROB13[6] = {0x9354ffffffffe38eULL, 0x0a395554e5c6aaaaULL,
                                       0xcd104635a790520cULL, 0xcc27c3d6fbd7063fULL,
                                       0x190937e76bc3e447ULL, 0x08ab05f8bdd54cdeULL};
static const uint64_t EXP_FROB23[6] = {0x26a9ffffffffc71cULL, 0x1472aaa9cb8d5555ULL,
                                       0x9a208c6b4f20a418ULL, 0x984f87adf7ae0c7fULL,
                                       0x32126fced787c88fULL, 0x11560bf17baa99bcULL};
// G1 cofactor h1 = (x-1)^2/3, 126 bits
static const uint64_t H1_LIMBS[2] = {0x8c00aaab0000aaabULL, 0x396c8c005555e156ULL};
// |x| (BLS parameter), 64 bits
static const uint64_t Z_PARAM = 0xD201000000010000ULL;

static inline bool fp_is_zero(const Fp& a) {
  uint64_t acc = 0;
  for (int i = 0; i < 6; i++) acc |= a.l[i];
  return acc == 0;
}

static inline bool fp_eq(const Fp& a, const Fp& b) {
  uint64_t acc = 0;
  for (int i = 0; i < 6; i++) acc |= a.l[i] ^ b.l[i];
  return acc == 0;
}

// a + b mod p
static inline Fp fp_add(const Fp& a, const Fp& b) {
  Fp r;
  u128 carry = 0;
  for (int i = 0; i < 6; i++) {
    carry += (u128)a.l[i] + b.l[i];
    r.l[i] = (uint64_t)carry;
    carry >>= 64;
  }
  // subtract p if >= p
  Fp s;
  u128 borrow = 0;
  for (int i = 0; i < 6; i++) {
    u128 d = (u128)r.l[i] - MOD.l[i] - borrow;
    s.l[i] = (uint64_t)d;
    borrow = (d >> 64) & 1;
  }
  if (carry || !borrow) return s;
  return r;
}

static inline Fp fp_sub(const Fp& a, const Fp& b) {
  Fp r;
  u128 borrow = 0;
  for (int i = 0; i < 6; i++) {
    u128 d = (u128)a.l[i] - b.l[i] - borrow;
    r.l[i] = (uint64_t)d;
    borrow = (d >> 64) & 1;
  }
  if (borrow) {
    u128 carry = 0;
    for (int i = 0; i < 6; i++) {
      carry += (u128)r.l[i] + MOD.l[i];
      r.l[i] = (uint64_t)carry;
      carry >>= 64;
    }
  }
  return r;
}

static inline Fp fp_neg(const Fp& a) {
  if (fp_is_zero(a)) return a;
  return fp_sub(FP_ZERO, a);
}

static inline Fp fp_dbl(const Fp& a) { return fp_add(a, a); }

// Montgomery multiplication (CIOS)
static Fp fp_mul(const Fp& a, const Fp& b) {
  uint64_t t[8] = {0, 0, 0, 0, 0, 0, 0, 0};
  for (int i = 0; i < 6; i++) {
    u128 carry = 0;
    for (int j = 0; j < 6; j++) {
      carry += (u128)t[j] + (u128)a.l[i] * b.l[j];
      t[j] = (uint64_t)carry;
      carry >>= 64;
    }
    carry += t[6];
    t[6] = (uint64_t)carry;
    t[7] = (uint64_t)(carry >> 64);
    uint64_t m = t[0] * PINV;
    carry = (u128)t[0] + (u128)m * MOD.l[0];
    carry >>= 64;
    for (int j = 1; j < 6; j++) {
      carry += (u128)t[j] + (u128)m * MOD.l[j];
      t[j - 1] = (uint64_t)carry;
      carry >>= 64;
    }
    carry += t[6];
    t[5] = (uint64_t)carry;
    t[6] = t[7] + (uint64_t)(carry >> 64);
  }
  Fp r;
  // final reduce: t[0..5] (+ t[6] overflow bit) mod p
  Fp s;
  u128 borrow = 0;
  for (int i = 0; i < 6; i++) {
    u128 d = (u128)t[i] - MOD.l[i] - borrow;
    s.l[i] = (uint64_t)d;
    borrow = (d >> 64) & 1;
  }
  if (t[6] || !borrow) {
    for (int i = 0; i < 6; i++) r.l[i] = s.l[i];
  } else {
    for (int i = 0; i < 6; i++) r.l[i] = t[i];
  }
  return r;
}

static inline Fp fp_sq(const Fp& a) { return fp_mul(a, a); }

// exponentiation by a plain little-endian limb exponent
static Fp fp_pow(const Fp& a, const uint64_t* e, int nlimbs) {
  Fp result = FP_ONE;
  Fp base = a;
  for (int i = 0; i < nlimbs; i++) {
    uint64_t w = e[i];
    for (int b = 0; b < 64; b++) {
      if (w & 1) result = fp_mul(result, base);
      base = fp_sq(base);
      w >>= 1;
    }
  }
  return result;
}

static inline Fp fp_inv(const Fp& a) { return fp_pow(a, EXP_PM2, 6); }

static void fp_from_be(const uint8_t* in, Fp* out) {
  Fp plain;
  for (int i = 0; i < 6; i++) {
    uint64_t v = 0;
    for (int j = 0; j < 8; j++) v = (v << 8) | in[(5 - i) * 8 + j];
    plain.l[i] = v;
  }
  *out = fp_mul(plain, R2);  // to Montgomery form
}

// out of Montgomery form into plain limbs
static void fp_plain(const Fp& a, uint64_t out[6]) {
  Fp one_scaled = {{1, 0, 0, 0, 0, 0}};
  Fp plain = fp_mul(a, one_scaled);
  for (int i = 0; i < 6; i++) out[i] = plain.l[i];
}

static void fp_to_be(const Fp& a, uint8_t* out) {
  uint64_t plain[6];
  fp_plain(a, plain);
  for (int i = 0; i < 6; i++) {
    uint64_t v = plain[5 - i];
    for (int j = 0; j < 8; j++) out[i * 8 + j] = (uint8_t)(v >> (56 - 8 * j));
  }
}

// lexicographic compare of standard-form values: a < b
static bool fp_std_less(const Fp& a, const Fp& b) {
  uint64_t pa[6], pb[6];
  fp_plain(a, pa);
  fp_plain(b, pb);
  for (int i = 5; i >= 0; i--) {
    if (pa[i] != pb[i]) return pa[i] < pb[i];
  }
  return false;
}

// sqrt for p ≡ 3 mod 4: a^((p+1)/4); returns false if non-residue
static bool fp_sqrt(const Fp& a, Fp* out) {
  Fp r = fp_pow(a, EXP_SQRT, 6);
  if (!fp_eq(fp_sq(r), a)) return false;
  *out = r;
  return true;
}

// ---------------------------------------------------------------------------
// Fp2 = Fp[u]/(u²+1)
// ---------------------------------------------------------------------------

struct Fp2 {
  Fp c0, c1;
};

static const Fp2 FP2_ZERO = {FP_ZERO, FP_ZERO};
static const Fp2 FP2_ONE = {FP_ONE, FP_ZERO};

static inline bool fp2_is_zero(const Fp2& a) {
  return fp_is_zero(a.c0) && fp_is_zero(a.c1);
}
static inline bool fp2_eq(const Fp2& a, const Fp2& b) {
  return fp_eq(a.c0, b.c0) && fp_eq(a.c1, b.c1);
}
static inline Fp2 fp2_add(const Fp2& a, const Fp2& b) {
  return {fp_add(a.c0, b.c0), fp_add(a.c1, b.c1)};
}
static inline Fp2 fp2_sub(const Fp2& a, const Fp2& b) {
  return {fp_sub(a.c0, b.c0), fp_sub(a.c1, b.c1)};
}
static inline Fp2 fp2_neg(const Fp2& a) { return {fp_neg(a.c0), fp_neg(a.c1)}; }
static inline Fp2 fp2_dbl(const Fp2& a) { return {fp_dbl(a.c0), fp_dbl(a.c1)}; }

static inline Fp2 fp2_mul(const Fp2& a, const Fp2& b) {
  Fp t0 = fp_mul(a.c0, b.c0);
  Fp t1 = fp_mul(a.c1, b.c1);
  Fp s = fp_mul(fp_add(a.c0, a.c1), fp_add(b.c0, b.c1));
  return {fp_sub(t0, t1), fp_sub(fp_sub(s, t0), t1)};
}

static inline Fp2 fp2_sq(const Fp2& a) {
  Fp t0 = fp_mul(fp_add(a.c0, a.c1), fp_sub(a.c0, a.c1));
  Fp t1 = fp_dbl(fp_mul(a.c0, a.c1));
  return {t0, t1};
}

static inline Fp2 fp2_scalar_fp(const Fp2& a, const Fp& k) {
  return {fp_mul(a.c0, k), fp_mul(a.c1, k)};
}

static inline Fp2 fp2_conj(const Fp2& a) { return {a.c0, fp_neg(a.c1)}; }

static inline Fp2 fp2_inv(const Fp2& a) {
  Fp d = fp_inv(fp_add(fp_sq(a.c0), fp_sq(a.c1)));
  return {fp_mul(a.c0, d), fp_neg(fp_mul(a.c1, d))};
}

// multiply by ξ = 1+u
static inline Fp2 fp2_mul_xi(const Fp2& a) {
  return {fp_sub(a.c0, a.c1), fp_add(a.c0, a.c1)};
}

static Fp2 fp2_pow(const Fp2& a, const uint64_t* e, int nlimbs) {
  Fp2 result = FP2_ONE;
  Fp2 base = a;
  for (int i = 0; i < nlimbs; i++) {
    uint64_t w = e[i];
    for (int b = 0; b < 64; b++) {
      if (w & 1) result = fp2_mul(result, base);
      base = fp2_sq(base);
      w >>= 1;
    }
  }
  return result;
}

// ---------------------------------------------------------------------------
// Fp6 = Fp2[v]/(v³ − ξ)
// ---------------------------------------------------------------------------

struct Fp6 {
  Fp2 c0, c1, c2;
};

static const Fp6 FP6_ZERO = {FP2_ZERO, FP2_ZERO, FP2_ZERO};
static const Fp6 FP6_ONE = {FP2_ONE, FP2_ZERO, FP2_ZERO};

static inline Fp6 fp6_add(const Fp6& a, const Fp6& b) {
  return {fp2_add(a.c0, b.c0), fp2_add(a.c1, b.c1), fp2_add(a.c2, b.c2)};
}
static inline Fp6 fp6_sub(const Fp6& a, const Fp6& b) {
  return {fp2_sub(a.c0, b.c0), fp2_sub(a.c1, b.c1), fp2_sub(a.c2, b.c2)};
}
static inline Fp6 fp6_neg(const Fp6& a) {
  return {fp2_neg(a.c0), fp2_neg(a.c1), fp2_neg(a.c2)};
}

static Fp6 fp6_mul(const Fp6& a, const Fp6& b) {
  Fp2 t0 = fp2_mul(a.c0, b.c0);
  Fp2 t1 = fp2_mul(a.c1, b.c1);
  Fp2 t2 = fp2_mul(a.c2, b.c2);
  Fp2 c0 = fp2_add(
      t0, fp2_mul_xi(fp2_sub(
              fp2_sub(fp2_mul(fp2_add(a.c1, a.c2), fp2_add(b.c1, b.c2)), t1),
              t2)));
  Fp2 c1 = fp2_add(
      fp2_sub(fp2_sub(fp2_mul(fp2_add(a.c0, a.c1), fp2_add(b.c0, b.c1)), t0),
              t1),
      fp2_mul_xi(t2));
  Fp2 c2 = fp2_add(
      fp2_sub(fp2_sub(fp2_mul(fp2_add(a.c0, a.c2), fp2_add(b.c0, b.c2)), t0),
              t2),
      t1);
  return {c0, c1, c2};
}

static inline Fp6 fp6_sq(const Fp6& a) { return fp6_mul(a, a); }

static inline Fp6 fp6_mul_by_v(const Fp6& a) {
  return {fp2_mul_xi(a.c2), a.c0, a.c1};
}

static Fp6 fp6_inv(const Fp6& a) {
  Fp2 t0 = fp2_sub(fp2_sq(a.c0), fp2_mul_xi(fp2_mul(a.c1, a.c2)));
  Fp2 t1 = fp2_sub(fp2_mul_xi(fp2_sq(a.c2)), fp2_mul(a.c0, a.c1));
  Fp2 t2 = fp2_sub(fp2_sq(a.c1), fp2_mul(a.c0, a.c2));
  Fp2 d = fp2_add(fp2_mul(a.c0, t0),
                  fp2_mul_xi(fp2_add(fp2_mul(a.c1, t2), fp2_mul(a.c2, t1))));
  Fp2 dinv = fp2_inv(d);
  return {fp2_mul(t0, dinv), fp2_mul(t1, dinv), fp2_mul(t2, dinv)};
}

// Frobenius constants (computed at load time)
static Fp2 FROB_G1C;   // ξ^((p-1)/6)
static Fp2 FROB6_C1;   // ξ^((p-1)/3)
static Fp2 FROB6_C2;   // ξ^(2(p-1)/3)

static Fp6 fp6_frobenius(const Fp6& a) {
  return {fp2_conj(a.c0), fp2_mul(fp2_conj(a.c1), FROB6_C1),
          fp2_mul(fp2_conj(a.c2), FROB6_C2)};
}

static Fp6 fp6_scale_fp2(const Fp6& a, const Fp2& s) {
  return {fp2_mul(a.c0, s), fp2_mul(a.c1, s), fp2_mul(a.c2, s)};
}

// ---------------------------------------------------------------------------
// Fp12 = Fp6[w]/(w² − v)
// ---------------------------------------------------------------------------

struct Fp12 {
  Fp6 c0, c1;
};

static const Fp12 FP12_ONE = {FP6_ONE, FP6_ZERO};

static inline bool fp12_eq(const Fp12& a, const Fp12& b) {
  return fp2_eq(a.c0.c0, b.c0.c0) && fp2_eq(a.c0.c1, b.c0.c1) &&
         fp2_eq(a.c0.c2, b.c0.c2) && fp2_eq(a.c1.c0, b.c1.c0) &&
         fp2_eq(a.c1.c1, b.c1.c1) && fp2_eq(a.c1.c2, b.c1.c2);
}

static Fp12 fp12_mul(const Fp12& a, const Fp12& b) {
  Fp6 t0 = fp6_mul(a.c0, b.c0);
  Fp6 t1 = fp6_mul(a.c1, b.c1);
  Fp6 c0 = fp6_add(t0, fp6_mul_by_v(t1));
  Fp6 c1 =
      fp6_sub(fp6_sub(fp6_mul(fp6_add(a.c0, a.c1), fp6_add(b.c0, b.c1)), t0), t1);
  return {c0, c1};
}

static Fp12 fp12_sq(const Fp12& a) {
  Fp6 t = fp6_mul(a.c0, a.c1);
  Fp6 c0 = fp6_sub(
      fp6_sub(fp6_mul(fp6_add(a.c0, a.c1), fp6_add(a.c0, fp6_mul_by_v(a.c1))),
              t),
      fp6_mul_by_v(t));
  return {c0, fp6_add(t, t)};
}

static inline Fp12 fp12_conj(const Fp12& a) { return {a.c0, fp6_neg(a.c1)}; }

static Fp12 fp12_inv(const Fp12& a) {
  Fp6 d = fp6_sub(fp6_sq(a.c0), fp6_mul_by_v(fp6_sq(a.c1)));
  Fp6 dinv = fp6_inv(d);
  return {fp6_mul(a.c0, dinv), fp6_neg(fp6_mul(a.c1, dinv))};
}

static Fp12 fp12_frobenius(const Fp12& a) {
  return {fp6_frobenius(a.c0), fp6_scale_fp2(fp6_frobenius(a.c1), FROB_G1C)};
}

static Fp12 fp12_frobenius2(const Fp12& a) {
  return fp12_frobenius(fp12_frobenius(a));
}

// ---------------------------------------------------------------------------
// Curve points (Jacobian), generic over Fp (G1) and Fp2 (G2)
// ---------------------------------------------------------------------------

template <class F>
struct FieldOps;

template <>
struct FieldOps<Fp> {
  static Fp zero() { return FP_ZERO; }
  static Fp one() { return FP_ONE; }
  static Fp add(const Fp& a, const Fp& b) { return fp_add(a, b); }
  static Fp sub(const Fp& a, const Fp& b) { return fp_sub(a, b); }
  static Fp neg(const Fp& a) { return fp_neg(a); }
  static Fp mul(const Fp& a, const Fp& b) { return fp_mul(a, b); }
  static Fp sq(const Fp& a) { return fp_sq(a); }
  static Fp inv(const Fp& a) { return fp_inv(a); }
  static bool is_zero(const Fp& a) { return fp_is_zero(a); }
  static bool eq(const Fp& a, const Fp& b) { return fp_eq(a, b); }
};

template <>
struct FieldOps<Fp2> {
  static Fp2 zero() { return FP2_ZERO; }
  static Fp2 one() { return FP2_ONE; }
  static Fp2 add(const Fp2& a, const Fp2& b) { return fp2_add(a, b); }
  static Fp2 sub(const Fp2& a, const Fp2& b) { return fp2_sub(a, b); }
  static Fp2 neg(const Fp2& a) { return fp2_neg(a); }
  static Fp2 mul(const Fp2& a, const Fp2& b) { return fp2_mul(a, b); }
  static Fp2 sq(const Fp2& a) { return fp2_sq(a); }
  static Fp2 inv(const Fp2& a) { return fp2_inv(a); }
  static bool is_zero(const Fp2& a) { return fp2_is_zero(a); }
  static bool eq(const Fp2& a, const Fp2& b) { return fp2_eq(a, b); }
};

template <class F>
struct Jac {
  F X, Y, Z;
  bool is_inf() const { return FieldOps<F>::is_zero(Z); }
};

template <class F>
struct Aff {
  F x, y;
  bool inf;
};

template <class F>
static Jac<F> jac_infinity() {
  return {FieldOps<F>::one(), FieldOps<F>::one(), FieldOps<F>::zero()};
}

template <class F>
static Jac<F> jac_from_aff(const Aff<F>& a) {
  if (a.inf) return jac_infinity<F>();
  return {a.x, a.y, FieldOps<F>::one()};
}

template <class F>
static Jac<F> jac_double(const Jac<F>& p) {
  using O = FieldOps<F>;
  if (p.is_inf()) return p;
  F A = O::sq(p.X);
  F B = O::sq(p.Y);
  F C = O::sq(B);
  F t = O::sq(O::add(p.X, B));
  F D = O::add(O::sub(O::sub(t, A), C), O::sub(O::sub(t, A), C));  // 2(..)
  F E = O::add(O::add(A, A), A);
  F Fv = O::sq(E);
  F X3 = O::sub(Fv, O::add(D, D));
  F eightC = O::add(O::add(O::add(C, C), O::add(C, C)),
                    O::add(O::add(C, C), O::add(C, C)));
  F Y3 = O::sub(O::mul(E, O::sub(D, X3)), eightC);
  F Z3 = O::add(O::mul(p.Y, p.Z), O::mul(p.Y, p.Z));
  return {X3, Y3, Z3};
}

// mixed addition: p (Jacobian) + q (affine, not infinity)
template <class F>
static Jac<F> jac_madd(const Jac<F>& p, const Aff<F>& q) {
  using O = FieldOps<F>;
  if (q.inf) return p;
  if (p.is_inf()) return jac_from_aff(q);
  F Z1Z1 = O::sq(p.Z);
  F U2 = O::mul(q.x, Z1Z1);
  F S2 = O::mul(O::mul(q.y, p.Z), Z1Z1);
  if (O::eq(U2, p.X)) {
    if (O::eq(S2, p.Y)) return jac_double(p);
    return jac_infinity<F>();
  }
  F H = O::sub(U2, p.X);
  F HH = O::sq(H);
  F HHH = O::mul(H, HH);
  F rr = O::sub(S2, p.Y);
  F V = O::mul(p.X, HH);
  F X3 = O::sub(O::sub(O::sq(rr), HHH), O::add(V, V));
  F Y3 = O::sub(O::mul(rr, O::sub(V, X3)), O::mul(p.Y, HHH));
  F Z3 = O::mul(p.Z, H);
  return {X3, Y3, Z3};
}

// full Jacobian addition
template <class F>
static Jac<F> jac_add(const Jac<F>& p, const Jac<F>& q) {
  using O = FieldOps<F>;
  if (p.is_inf()) return q;
  if (q.is_inf()) return p;
  F Z1Z1 = O::sq(p.Z);
  F Z2Z2 = O::sq(q.Z);
  F U1 = O::mul(p.X, Z2Z2);
  F U2 = O::mul(q.X, Z1Z1);
  F S1 = O::mul(O::mul(p.Y, q.Z), Z2Z2);
  F S2 = O::mul(O::mul(q.Y, p.Z), Z1Z1);
  if (O::eq(U1, U2)) {
    if (O::eq(S1, S2)) return jac_double(p);
    return jac_infinity<F>();
  }
  F H = O::sub(U2, U1);
  F HH = O::sq(H);
  F HHH = O::mul(H, HH);
  F rr = O::sub(S2, S1);
  F V = O::mul(U1, HH);
  F X3 = O::sub(O::sub(O::sq(rr), HHH), O::add(V, V));
  F Y3 = O::sub(O::mul(rr, O::sub(V, X3)), O::mul(S1, HHH));
  F Z3 = O::mul(O::mul(p.Z, q.Z), H);
  return {X3, Y3, Z3};
}

template <class F>
static Aff<F> jac_to_aff(const Jac<F>& p) {
  using O = FieldOps<F>;
  if (p.is_inf()) return {O::zero(), O::zero(), true};
  F zinv = O::inv(p.Z);
  F zinv2 = O::sq(zinv);
  F zinv3 = O::mul(zinv2, zinv);
  return {O::mul(p.X, zinv2), O::mul(p.Y, zinv3), false};
}

// Batch Jacobian→affine: ONE field inversion for n points (Montgomery
// trick) — the per-point inversion (~450 muls via Fermat) was about
// half the fixed-base comb's cost per scalar.
template <class F>
static void jac_batch_to_aff(const std::vector<Jac<F>>& pts,
                             std::vector<Aff<F>>& out) {
  using O = FieldOps<F>;
  size_t n = pts.size();
  out.resize(n);
  std::vector<F> prefix(n);
  F acc = O::one();
  for (size_t i = 0; i < n; i++) {
    prefix[i] = acc;
    if (!pts[i].is_inf()) acc = O::mul(acc, pts[i].Z);
  }
  F inv = O::inv(acc);
  for (size_t i = n; i-- > 0;) {
    if (pts[i].is_inf()) {
      out[i] = {O::zero(), O::zero(), true};
      continue;
    }
    F zinv = O::mul(inv, prefix[i]);
    inv = O::mul(inv, pts[i].Z);
    F zinv2 = O::sq(zinv);
    F zinv3 = O::mul(zinv2, zinv);
    out[i] = {O::mul(pts[i].X, zinv2), O::mul(pts[i].Y, zinv3), false};
  }
}

// scalar multiplication, scalar as big-endian bytes
template <class F>
static Jac<F> jac_mul_be(const Aff<F>& p, const uint8_t* k, size_t klen) {
  Jac<F> acc = jac_infinity<F>();
  bool started = false;
  for (size_t i = 0; i < klen; i++) {
    for (int b = 7; b >= 0; b--) {
      if (started) acc = jac_double(acc);
      if ((k[i] >> b) & 1) {
        acc = jac_madd(acc, p);
        started = true;
      }
    }
  }
  return acc;
}

// scalar multiplication by little-endian limb scalar
template <class F>
static Jac<F> jac_mul_limbs(const Jac<F>& p, const uint64_t* k, int nlimbs) {
  Jac<F> acc = jac_infinity<F>();
  int top = nlimbs * 64 - 1;
  while (top >= 0 && !((k[top / 64] >> (top % 64)) & 1)) top--;
  for (int i = top; i >= 0; i--) {
    acc = jac_double(acc);
    if ((k[i / 64] >> (i % 64)) & 1) acc = jac_add(acc, p);
  }
  return acc;
}

// ---------------------------------------------------------------------------
// Pippenger MSM
// ---------------------------------------------------------------------------

template <class F>
static Jac<F> msm(const std::vector<Aff<F>>& pts,
                  const std::vector<std::vector<uint8_t>>& scalars) {
  size_t n = pts.size();
  if (n == 0) return jac_infinity<F>();
  // window size minimizing ceil(256/c)·(n + 2^c + 2^c) point adds
  int c = 2;
  double best = 1e300;
  for (int w = 2; w <= 14; w++) {
    double cost = ((256 + w - 1) / w) * ((double)n + 2.0 * (1u << w));
    if (cost < best) {
      best = cost;
      c = w;
    }
  }
  const int nbits = 256;
  int nwin = (nbits + c - 1) / c;
  Jac<F> total = jac_infinity<F>();
  std::vector<Jac<F>> buckets((size_t)1 << c);
  for (int w = nwin - 1; w >= 0; w--) {
    if (!total.is_inf()) {
      for (int i = 0; i < c; i++) total = jac_double(total);
    }
    size_t nbkt = ((size_t)1 << c) - 1;
    for (size_t i = 0; i <= nbkt; i++) buckets[i] = jac_infinity<F>();
    int lo = w * c;
    for (size_t i = 0; i < n; i++) {
      if (pts[i].inf) continue;
      // extract bits [lo, lo+c) of the big-endian scalar
      uint32_t idx = 0;
      for (int b = c - 1; b >= 0; b--) {
        int bit = lo + b;
        if (bit >= nbits) continue;
        int byte = 31 - bit / 8;
        idx = (idx << 1) | ((scalars[i][byte] >> (bit % 8)) & 1);
      }
      if (idx) buckets[idx] = jac_madd(buckets[idx], pts[i]);
    }
    Jac<F> running = jac_infinity<F>();
    Jac<F> sum = jac_infinity<F>();
    for (size_t b = nbkt; b >= 1; b--) {
      running = jac_add(running, buckets[b]);
      sum = jac_add(sum, running);
    }
    total = jac_add(total, sum);
  }
  return total;
}

// ---------------------------------------------------------------------------
// Pairing
// ---------------------------------------------------------------------------

struct LineEval {
  Fp2 a0, a1, b1;  // line = (a0 + a1·v) + (b1·v)·w, all pre-scaled
};

// sparse Fq6 multiplications (mirror pairing.py)
static Fp6 fp6_mul_by_01(const Fp6& c, const Fp2& s0, const Fp2& s1) {
  return {fp2_add(fp2_mul(c.c0, s0), fp2_mul_xi(fp2_mul(c.c2, s1))),
          fp2_add(fp2_mul(c.c0, s1), fp2_mul(c.c1, s0)),
          fp2_add(fp2_mul(c.c1, s1), fp2_mul(c.c2, s0))};
}

static Fp6 fp6_mul_by_1(const Fp6& c, const Fp2& s1) {
  return {fp2_mul_xi(fp2_mul(c.c2, s1)), fp2_mul(c.c0, s1), fp2_mul(c.c1, s1)};
}

static Fp12 mul_by_line(const Fp12& f, const LineEval& l) {
  Fp6 t0 = fp6_mul_by_01(f.c0, l.a0, l.a1);
  Fp6 t1 = fp6_mul_by_1(f.c1, l.b1);
  Fp6 fs = fp6_add(f.c0, f.c1);
  Fp6 c1 = fp6_sub(fp6_sub(fp6_mul_by_01(fs, l.a0, fp2_add(l.a1, l.b1)), t0), t1);
  Fp6 c0 = fp6_add(t0, fp6_mul_by_v(t1));
  return {c0, c1};
}

// Doubling step with Jacobian T on the twist; line scaled by 2YZ³ ∈ Fq2*
// (the scale factor lies in a subfield and is killed by the final
// exponentiation, so pairing values match the affine Python oracle).
static LineEval line_dbl(Jac<Fp2>& T, const Fp& xP, const Fp& yP) {
  Fp2 A = fp2_sq(T.X);             // X²
  Fp2 B = fp2_sq(T.Y);             // Y²
  Fp2 C = fp2_sq(B);               // Y⁴
  Fp2 t = fp2_sq(fp2_add(T.X, B));
  Fp2 D2 = fp2_sub(fp2_sub(t, A), C);
  Fp2 D = fp2_add(D2, D2);         // 2·2XY² = 4XY²... D = 2((X+B)²−A−C)
  Fp2 E = fp2_add(fp2_add(A, A), A);  // 3X²
  Fp2 Fv = fp2_sq(E);
  Fp2 Zsq = fp2_sq(T.Z);
  Fp2 X3 = fp2_sub(Fv, fp2_add(D, D));
  Fp2 eightC = fp2_dbl(fp2_dbl(fp2_dbl(C)));
  Fp2 Y3 = fp2_sub(fp2_mul(E, fp2_sub(D, X3)), eightC);
  Fp2 Z3 = fp2_dbl(fp2_mul(T.Y, T.Z));
  LineEval l;
  l.a0 = fp2_sub(fp2_mul(E, T.X), fp2_dbl(B));      // 3X³ − 2Y²
  l.a1 = fp2_scalar_fp(fp2_neg(fp2_mul(E, Zsq)), xP);  // −3X²Z²·xP
  l.b1 = fp2_scalar_fp(fp2_mul(Z3, Zsq), yP);       // 2YZ³·yP
  T = {X3, Y3, Z3};
  return l;
}

// Addition step (T += Q, Q affine); line scaled by Z·H = Z3 ∈ Fq2*
static LineEval line_add(Jac<Fp2>& T, const Aff<Fp2>& Q, const Fp& xP,
                         const Fp& yP) {
  Fp2 Z1Z1 = fp2_sq(T.Z);
  Fp2 U2 = fp2_mul(Q.x, Z1Z1);
  Fp2 S2 = fp2_mul(fp2_mul(Q.y, T.Z), Z1Z1);
  Fp2 H = fp2_sub(U2, T.X);
  Fp2 rr = fp2_sub(S2, T.Y);
  Fp2 HH = fp2_sq(H);
  Fp2 HHH = fp2_mul(H, HH);
  Fp2 V = fp2_mul(T.X, HH);
  Fp2 X3 = fp2_sub(fp2_sub(fp2_sq(rr), HHH), fp2_add(V, V));
  Fp2 Y3 = fp2_sub(fp2_mul(rr, fp2_sub(V, X3)), fp2_mul(T.Y, HHH));
  Fp2 Z3 = fp2_mul(T.Z, H);
  LineEval l;
  l.a0 = fp2_sub(fp2_mul(rr, Q.x), fp2_mul(Z3, Q.y));  // r·xQ − ZH·yQ
  l.a1 = fp2_scalar_fp(fp2_neg(rr), xP);
  l.b1 = fp2_scalar_fp(Z3, yP);
  T = {X3, Y3, Z3};
  return l;
}

static Fp12 miller_loop(const Aff<Fp>& p, const Aff<Fp2>& q) {
  if (p.inf || q.inf) return FP12_ONE;
  Jac<Fp2> T = jac_from_aff(q);
  Fp12 f = FP12_ONE;
  // iterate bits of Z_PARAM from the second-most-significant down
  int top = 63;
  while (top >= 0 && !((Z_PARAM >> top) & 1)) top--;
  for (int i = top - 1; i >= 0; i--) {
    f = fp12_sq(f);
    LineEval l = line_dbl(T, p.x, p.y);
    f = mul_by_line(f, l);
    if ((Z_PARAM >> i) & 1) {
      LineEval l2 = line_add(T, q, p.x, p.y);
      f = mul_by_line(f, l2);
    }
  }
  return fp12_conj(f);  // parameter x < 0
}

static Fp12 exp_by_z(const Fp12& m) {
  Fp12 result = m;
  int top = 63;
  while (top >= 0 && !((Z_PARAM >> top) & 1)) top--;
  for (int i = top - 1; i >= 0; i--) {
    result = fp12_sq(result);
    if ((Z_PARAM >> i) & 1) result = fp12_mul(result, m);
  }
  return result;
}

static Fp12 exp_by_x(const Fp12& m) { return fp12_conj(exp_by_z(m)); }

static Fp12 final_exponentiation(const Fp12& f0) {
  // easy part: f^((p^6−1)(p^2+1))
  Fp12 f = fp12_mul(fp12_conj(f0), fp12_inv(f0));
  f = fp12_mul(fp12_frobenius2(f), f);
  Fp12 m = f;
  // hard part ×3 (matches pairing.py exactly)
  Fp12 t0 = fp12_mul(exp_by_x(m), fp12_conj(m));
  t0 = fp12_mul(exp_by_x(t0), fp12_conj(t0));
  Fp12 t1 = fp12_mul(exp_by_x(t0), fp12_frobenius(t0));
  Fp12 t3 = exp_by_x(exp_by_x(t1));
  Fp12 out = fp12_mul(fp12_mul(t3, fp12_frobenius2(t1)), fp12_conj(t1));
  return fp12_mul(out, fp12_mul(m, fp12_sq(m)));
}

// ---------------------------------------------------------------------------
// SHA-512 (for hash_to_fq / hash_to_g1)
// ---------------------------------------------------------------------------

static const uint64_t K512[80] = {
    0x428a2f98d728ae22ULL, 0x7137449123ef65cdULL, 0xb5c0fbcfec4d3b2fULL,
    0xe9b5dba58189dbbcULL, 0x3956c25bf348b538ULL, 0x59f111f1b605d019ULL,
    0x923f82a4af194f9bULL, 0xab1c5ed5da6d8118ULL, 0xd807aa98a3030242ULL,
    0x12835b0145706fbeULL, 0x243185be4ee4b28cULL, 0x550c7dc3d5ffb4e2ULL,
    0x72be5d74f27b896fULL, 0x80deb1fe3b1696b1ULL, 0x9bdc06a725c71235ULL,
    0xc19bf174cf692694ULL, 0xe49b69c19ef14ad2ULL, 0xefbe4786384f25e3ULL,
    0x0fc19dc68b8cd5b5ULL, 0x240ca1cc77ac9c65ULL, 0x2de92c6f592b0275ULL,
    0x4a7484aa6ea6e483ULL, 0x5cb0a9dcbd41fbd4ULL, 0x76f988da831153b5ULL,
    0x983e5152ee66dfabULL, 0xa831c66d2db43210ULL, 0xb00327c898fb213fULL,
    0xbf597fc7beef0ee4ULL, 0xc6e00bf33da88fc2ULL, 0xd5a79147930aa725ULL,
    0x06ca6351e003826fULL, 0x142929670a0e6e70ULL, 0x27b70a8546d22ffcULL,
    0x2e1b21385c26c926ULL, 0x4d2c6dfc5ac42aedULL, 0x53380d139d95b3dfULL,
    0x650a73548baf63deULL, 0x766a0abb3c77b2a8ULL, 0x81c2c92e47edaee6ULL,
    0x92722c851482353bULL, 0xa2bfe8a14cf10364ULL, 0xa81a664bbc423001ULL,
    0xc24b8b70d0f89791ULL, 0xc76c51a30654be30ULL, 0xd192e819d6ef5218ULL,
    0xd69906245565a910ULL, 0xf40e35855771202aULL, 0x106aa07032bbd1b8ULL,
    0x19a4c116b8d2d0c8ULL, 0x1e376c085141ab53ULL, 0x2748774cdf8eeb99ULL,
    0x34b0bcb5e19b48a8ULL, 0x391c0cb3c5c95a63ULL, 0x4ed8aa4ae3418acbULL,
    0x5b9cca4f7763e373ULL, 0x682e6ff3d6b2b8a3ULL, 0x748f82ee5defb2fcULL,
    0x78a5636f43172f60ULL, 0x84c87814a1f0ab72ULL, 0x8cc702081a6439ecULL,
    0x90befffa23631e28ULL, 0xa4506cebde82bde9ULL, 0xbef9a3f7b2c67915ULL,
    0xc67178f2e372532bULL, 0xca273eceea26619cULL, 0xd186b8c721c0c207ULL,
    0xeada7dd6cde0eb1eULL, 0xf57d4f7fee6ed178ULL, 0x06f067aa72176fbaULL,
    0x0a637dc5a2c898a6ULL, 0x113f9804bef90daeULL, 0x1b710b35131c471bULL,
    0x28db77f523047d84ULL, 0x32caab7b40c72493ULL, 0x3c9ebe0a15c9bebcULL,
    0x431d67c49c100d4cULL, 0x4cc5d4becb3e42b6ULL, 0x597f299cfc657e2aULL,
    0x5fcb6fab3ad6faecULL, 0x6c44198c4a475817ULL};

static inline uint64_t rotr64(uint64_t x, int n) {
  return (x >> n) | (x << (64 - n));
}

static void sha512(const uint8_t* data, size_t len, uint8_t out[64]) {
  uint64_t h[8] = {0x6a09e667f3bcc908ULL, 0xbb67ae8584caa73bULL,
                   0x3c6ef372fe94f82bULL, 0xa54ff53a5f1d36f1ULL,
                   0x510e527fade682d1ULL, 0x9b05688c2b3e6c1fULL,
                   0x1f83d9abfb41bd6bULL, 0x5be0cd19137e2179ULL};
  size_t total = len;
  size_t padded = ((len + 17 + 127) / 128) * 128;
  std::vector<uint8_t> buf(padded, 0);
  memcpy(buf.data(), data, len);
  buf[len] = 0x80;
  u128 bits = (u128)total * 8;
  for (int i = 0; i < 16; i++)
    buf[padded - 1 - i] = (uint8_t)(bits >> (8 * i));
  for (size_t blk = 0; blk < padded; blk += 128) {
    uint64_t w[80];
    for (int i = 0; i < 16; i++) {
      uint64_t v = 0;
      for (int j = 0; j < 8; j++) v = (v << 8) | buf[blk + i * 8 + j];
      w[i] = v;
    }
    for (int i = 16; i < 80; i++) {
      uint64_t s0 = rotr64(w[i - 15], 1) ^ rotr64(w[i - 15], 8) ^ (w[i - 15] >> 7);
      uint64_t s1 = rotr64(w[i - 2], 19) ^ rotr64(w[i - 2], 61) ^ (w[i - 2] >> 6);
      w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint64_t a = h[0], b = h[1], c = h[2], d = h[3], e = h[4], f = h[5],
             g = h[6], hh = h[7];
    for (int i = 0; i < 80; i++) {
      uint64_t S1 = rotr64(e, 14) ^ rotr64(e, 18) ^ rotr64(e, 41);
      uint64_t ch = (e & f) ^ (~e & g);
      uint64_t t1 = hh + S1 + ch + K512[i] + w[i];
      uint64_t S0 = rotr64(a, 28) ^ rotr64(a, 34) ^ rotr64(a, 39);
      uint64_t maj = (a & b) ^ (a & c) ^ (b & c);
      uint64_t t2 = S0 + maj;
      hh = g; g = f; f = e; e = d + t1; d = c; c = b; b = a; a = t1 + t2;
    }
    h[0] += a; h[1] += b; h[2] += c; h[3] += d;
    h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
  }
  for (int i = 0; i < 8; i++)
    for (int j = 0; j < 8; j++) out[i * 8 + j] = (uint8_t)(h[i] >> (56 - 8 * j));
}

// reduce a 512-bit big-endian value mod p (shift-subtract; plain limbs)
static void reduce512_mod_p(const uint8_t in[64], uint64_t out[6]) {
  uint64_t r[7] = {0, 0, 0, 0, 0, 0, 0};
  for (int byte = 0; byte < 64; byte++) {
    for (int bit = 7; bit >= 0; bit--) {
      // r = 2r + next bit
      uint64_t carry = (in[byte] >> bit) & 1;
      for (int i = 0; i < 7; i++) {
        uint64_t nc = r[i] >> 63;
        r[i] = (r[i] << 1) | carry;
        carry = nc;
      }
      // if r >= p: r -= p
      bool ge = r[6] != 0;
      if (!ge) {
        ge = true;
        for (int i = 5; i >= 0; i--) {
          if (r[i] != MOD.l[i]) {
            ge = r[i] > MOD.l[i];
            break;
          }
        }
      }
      if (ge) {
        u128 borrow = 0;
        for (int i = 0; i < 6; i++) {
          u128 d = (u128)r[i] - MOD.l[i] - borrow;
          r[i] = (uint64_t)d;
          borrow = (d >> 64) & 1;
        }
        r[6] -= (uint64_t)borrow;  // borrow out of low 6 limbs
      }
    }
  }
  for (int i = 0; i < 6; i++) out[i] = r[i];
}

// ---------------------------------------------------------------------------
// Wire conversion helpers
// ---------------------------------------------------------------------------

static bool buf_is_zero(const uint8_t* b, size_t n) {
  uint8_t acc = 0;
  for (size_t i = 0; i < n; i++) acc |= b[i];
  return acc == 0;
}

static Aff<Fp> g1_from_wire(const uint8_t in[96]) {
  if (buf_is_zero(in, 96)) return {FP_ZERO, FP_ZERO, true};
  Aff<Fp> a;
  a.inf = false;
  fp_from_be(in, &a.x);
  fp_from_be(in + 48, &a.y);
  return a;
}

static void g1_to_wire(const Aff<Fp>& a, uint8_t out[96]) {
  if (a.inf) {
    memset(out, 0, 96);
    return;
  }
  fp_to_be(a.x, out);
  fp_to_be(a.y, out + 48);
}

static Aff<Fp2> g2_from_wire(const uint8_t in[192]) {
  if (buf_is_zero(in, 192)) return {FP2_ZERO, FP2_ZERO, true};
  Aff<Fp2> a;
  a.inf = false;
  fp_from_be(in, &a.x.c0);
  fp_from_be(in + 48, &a.x.c1);
  fp_from_be(in + 96, &a.y.c0);
  fp_from_be(in + 144, &a.y.c1);
  return a;
}

static void g2_to_wire(const Aff<Fp2>& a, uint8_t out[192]) {
  if (a.inf) {
    memset(out, 0, 192);
    return;
  }
  fp_to_be(a.x.c0, out);
  fp_to_be(a.x.c1, out + 48);
  fp_to_be(a.y.c0, out + 96);
  fp_to_be(a.y.c1, out + 144);
}

static void fp12_to_wire(const Fp12& f, uint8_t out[576]) {
  const Fp2* cs[6] = {&f.c0.c0, &f.c0.c1, &f.c0.c2, &f.c1.c0, &f.c1.c1, &f.c1.c2};
  for (int i = 0; i < 6; i++) {
    fp_to_be(cs[i]->c0, out + i * 96);
    fp_to_be(cs[i]->c1, out + i * 96 + 48);
  }
}

// ---------------------------------------------------------------------------
// Init (Frobenius constants) — runs at library load
// ---------------------------------------------------------------------------

static const Fp2 XI = {FP_ONE, FP_ONE};  // ξ = 1 + u

struct BlsInit {
  BlsInit() {
    FROB_G1C = fp2_pow(XI, EXP_FROB16, 6);
    FROB6_C1 = fp2_pow(XI, EXP_FROB13, 6);
    FROB6_C2 = fp2_pow(XI, EXP_FROB23, 6);
  }
};
static BlsInit _init;

// ---------------------------------------------------------------------------
// Fr: the 255-bit scalar field (group order r), 4x64 limbs, Montgomery
// form with R = 2^256.  Powers the DKG's bivariate-polynomial algebra
// (sync_key_gen.rs:268-299, :449): row-coefficient and value-grid
// matrix products that would be hundreds of millions of Python bigint
// multiplications at co-simulation scale.
// ---------------------------------------------------------------------------

struct Fr {
  uint64_t l[4];
};

static const Fr FR_MOD = {{0xffffffff00000001ULL, 0x53bda402fffe5bfeULL,
                           0x3339d80809a1d805ULL, 0x73eda753299d7d48ULL}};
static const Fr FR_R2 = {{0xc999e990f3f29c6dULL, 0x2b6cedcb87925c23ULL,
                          0x05d314967254398fULL, 0x0748d9d99f59ff11ULL}};
static const uint64_t FR_NINV = 0xfffffffeffffffffULL;  // -r^{-1} mod 2^64
static const Fr FR_ONE_PLAIN = {{1, 0, 0, 0}};

static inline void fr_cond_sub(Fr& a) {
  // branchless: compute a - p, select on the final borrow
  uint64_t s[4];
  uint64_t borrow = 0;
  for (int i = 0; i < 4; i++) {
    u128 cur = (u128)a.l[i] - FR_MOD.l[i] - borrow;
    s[i] = (uint64_t)cur;
    borrow = (uint64_t)(cur >> 64) & 1;
  }
  uint64_t keep = 0 - borrow;  // all-ones if a < p (keep a)
  for (int i = 0; i < 4; i++)
    a.l[i] = (a.l[i] & keep) | (s[i] & ~keep);
}

static inline Fr fr_add(const Fr& a, const Fr& b) {
  Fr r;
  u128 carry = 0;
  for (int i = 0; i < 4; i++) {
    u128 cur = (u128)a.l[i] + b.l[i] + (uint64_t)carry;
    r.l[i] = (uint64_t)cur;
    carry = cur >> 64;
  }
  // r < 2^255 + 2^255 < 2^256: no limb overflow; one conditional subtract
  fr_cond_sub(r);
  return r;
}

// CIOS Montgomery multiplication, 4 limbs
static inline Fr fr_mont_mul(const Fr& a, const Fr& b) {
  uint64_t t[6] = {0, 0, 0, 0, 0, 0};
  for (int i = 0; i < 4; i++) {
    u128 carry = 0;
    for (int j = 0; j < 4; j++) {
      u128 cur = (u128)a.l[j] * b.l[i] + t[j] + (uint64_t)carry;
      t[j] = (uint64_t)cur;
      carry = cur >> 64;
    }
    u128 cur = (u128)t[4] + (uint64_t)carry;
    t[4] = (uint64_t)cur;
    t[5] = (uint64_t)(cur >> 64);
    uint64_t m = t[0] * FR_NINV;
    u128 c0 = (u128)m * FR_MOD.l[0] + t[0];
    carry = c0 >> 64;
    for (int j = 1; j < 4; j++) {
      u128 cur2 = (u128)m * FR_MOD.l[j] + t[j] + (uint64_t)carry;
      t[j - 1] = (uint64_t)cur2;
      carry = cur2 >> 64;
    }
    u128 cur3 = (u128)t[4] + (uint64_t)carry;
    t[3] = (uint64_t)cur3;
    t[4] = t[5] + (uint64_t)(cur3 >> 64);
  }
  Fr r = {{t[0], t[1], t[2], t[3]}};
  // r < 2p here (p < 2^255 keeps t[4] zero); reduce to canonical
  fr_cond_sub(r);
  return r;
}

static inline Fr fr_from_be(const uint8_t* in) {
  Fr r;
  for (int i = 0; i < 4; i++) {
    uint64_t v = 0;
    for (int j = 0; j < 8; j++) v = (v << 8) | in[(3 - i) * 8 + j];
    r.l[i] = v;
  }
  // tolerate any raw 256-bit input: 2^256 < 3r (r is 255-bit), so two
  // conditional subtracts reduce the whole range to canonical
  fr_cond_sub(r);
  fr_cond_sub(r);
  return r;
}

static inline void fr_to_be(const Fr& a, uint8_t* out) {
  for (int i = 0; i < 4; i++) {
    uint64_t v = a.l[i];
    for (int j = 7; j >= 0; j--) {
      out[(3 - i) * 8 + j] = (uint8_t)v;
      v >>= 8;
    }
  }
}

}  // namespace bls

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

using namespace bls;

// Many scalar-muls of ONE shared base point, individual outputs — the
// co-simulation shapes (every validator signing one nonce; every
// validator's decryption share of one ciphertext's U).  Fixed-base
// comb, shared by G1 and G2: precompute T[j][d] = d·2^(wbits·j)·P
// once (normalized to affine with ONE batch inversion so the
// per-scalar loop runs mixed adds), then each scalar is ≤ 256/wbits
// mixed additions with no doublings; outputs are batch-normalized
// with one more inversion.  Window width by batch size: below n = 16
// no table amortizes and the plain double-and-add loop runs; the
// 4-bit table (~1k adds) serves 16 ≤ n < 256; the 8-bit table
// (~8.1k adds, 32 adds/scalar saved) wins from n ≥ 256 (the N=1024
// epoch stages ~10⁶ of these per epoch, the shapes this is built for).
template <class F, size_t WIRE, Aff<F> (*FROM)(const uint8_t*),
          void (*TO)(const Aff<F>&, uint8_t*)>
static void comb_mul_many(uint64_t n, const uint8_t* p, const uint8_t* ks,
                          uint8_t* out) {
  Aff<F> a = FROM(p);
  if (n == 0) return;
  if (n < 16) {  // any table beats nothing only past a few scalars
    for (uint64_t i = 0; i < n; ++i) {
      Jac<F> r = jac_mul_be(a, ks + i * 32, 32);
      TO(jac_to_aff(r), out + i * WIRE);
    }
    return;
  }
  // window width by batch size: the 8-bit table costs ~8.1k adds vs
  // the 4-bit table's ~1k and saves 32 adds/scalar, so it wins past
  // ~256 scalars; mid-size batches keep the 4-bit table
  const int wbits = (n >= 256) ? 8 : 4;
  const int nwin = 256 / wbits;  // windows per 256-bit scalar
  const int tmax = (1 << wbits) - 1;  // nonzero digits per window
  // T[j][d-1] = d * 2^(wbits*j) * P.  The window bases 2^(wbits·j)·P
  // are normalized to affine first (one batch inversion), so the
  // ~nwin·tmax row fills run MIXED adds (11 field muls) instead of
  // full Jacobian adds (16) — at the epoch staging shape (974 bases
  // per epoch) the table build was ~20% of the whole call.
  std::vector<Jac<F>> pows(nwin);
  Jac<F> cur = jac_madd(jac_infinity<F>(), a);  // P as Jacobian
  for (int j = 0; j < nwin; ++j) {
    pows[j] = cur;
    if (j < nwin - 1)
      for (int t = 0; t < wbits; ++t) cur = jac_double(cur);
  }
  static thread_local std::vector<Aff<F>> pow_aff;
  jac_batch_to_aff(pows, pow_aff);
  static thread_local std::vector<Jac<F>> table;
  table.assign(nwin * tmax, jac_infinity<F>());
  for (int j = 0; j < nwin; ++j) {
    Jac<F> acc = jac_madd(jac_infinity<F>(), pow_aff[j]);
    for (int d = 1; d <= tmax; ++d) {
      table[j * tmax + d - 1] = acc;
      if (d < tmax) acc = jac_madd(acc, pow_aff[j]);
    }
  }
  static thread_local std::vector<Aff<F>> table_aff;
  jac_batch_to_aff(table, table_aff);
  std::vector<Jac<F>> res(n);
  for (uint64_t i = 0; i < n; ++i) {
    const uint8_t* k = ks + i * 32;  // big-endian 32 bytes
    Jac<F> acc = jac_infinity<F>();
    for (int j = 0; j < nwin; ++j) {
      // window j covers bits [wbits·j, wbits·(j+1))
      int bit = wbits * j;
      uint8_t d = (k[31 - bit / 8] >> (bit % 8)) & tmax;
      if (d) acc = jac_madd(acc, table_aff[j * tmax + d - 1]);
    }
    res[i] = acc;
  }
  std::vector<Aff<F>> affs;
  jac_batch_to_aff(res, affs);
  for (uint64_t i = 0; i < n; ++i) TO(affs[i], out + i * WIRE);
}


extern "C" {

void hb_g1_mul(const uint8_t* p, const uint8_t* k, uint8_t* out) {
  Aff<Fp> a = g1_from_wire(p);
  Jac<Fp> r = jac_mul_be(a, k, 32);
  g1_to_wire(jac_to_aff(r), out);
}

void hb_g2_mul(const uint8_t* p, const uint8_t* k, uint8_t* out) {
  Aff<Fp2> a = g2_from_wire(p);
  Jac<Fp2> r = jac_mul_be(a, k, 32);
  g2_to_wire(jac_to_aff(r), out);
}

void hb_g1_mul_many(uint64_t n, const uint8_t* p, const uint8_t* ks,
                    uint8_t* out) {
  comb_mul_many<Fp, 96, g1_from_wire, g1_to_wire>(n, p, ks, out);
}

void hb_g1_msm(uint64_t n, const uint8_t* pts, const uint8_t* ks, uint8_t* out) {
  std::vector<Aff<Fp>> apts(n);
  std::vector<std::vector<uint8_t>> scalars(n);
  for (uint64_t i = 0; i < n; i++) {
    apts[i] = g1_from_wire(pts + 96 * i);
    scalars[i].assign(ks + 32 * i, ks + 32 * i + 32);
  }
  g1_to_wire(jac_to_aff(msm(apts, scalars)), out);
}

void hb_g2_msm(uint64_t n, const uint8_t* pts, const uint8_t* ks, uint8_t* out) {
  std::vector<Aff<Fp2>> apts(n);
  std::vector<std::vector<uint8_t>> scalars(n);
  for (uint64_t i = 0; i < n; i++) {
    apts[i] = g2_from_wire(pts + 192 * i);
    scalars[i].assign(ks + 32 * i, ks + 32 * i + 32);
  }
  g2_to_wire(jac_to_aff(msm(apts, scalars)), out);
}

// The epoch staging matrix (the per-node decrypt_share work of
// honey_badger.rs:422-444, deduplicated network-wide by the
// co-simulation): out[b][s] = ks[s]·base_b for EVERY (base, scalar)
// pair in ONE call — per base the fixed-base comb of comb_mul_many,
// with the 32-byte-scalar buffer shared across bases and none of the
// per-base ctypes crossing / scalar re-marshalling / output slicing
// the per-ciphertext Python loop paid (r5 epoch phase profile:
// dec_staging was the top term at 64 s/epoch).  out is base-major,
// n_bases × n_scalars × 96 bytes.
void hb_g1_mul_outer(uint64_t n_bases, uint64_t n_scalars,
                     const uint8_t* bases, const uint8_t* ks,
                     uint8_t* out) {
  for (uint64_t b = 0; b < n_bases; ++b)
    comb_mul_many<Fp, 96, g1_from_wire, g1_to_wire>(
        n_scalars, bases + b * 96, ks, out + b * n_scalars * 96);
}

// Many MSMs over ONE shared scalar vector — the combine shape: every
// proposer's plaintext is the Lagrange combination of its lowest t+1
// valid shares with one weight vector (honey_badger.rs:340 at
// co-simulation scale; r5 phase profile: 974 per-proposer Python
// combines cost 22 s/epoch).  pts row-major (n_msms × n_pts × 96 B),
// out n_msms × 96 B.
void hb_g1_msm_many(uint64_t n_msms, uint64_t n_pts, const uint8_t* pts,
                    const uint8_t* ks, uint8_t* out) {
  std::vector<std::vector<uint8_t>> scalars(n_pts);
  for (uint64_t i = 0; i < n_pts; i++)
    scalars[i].assign(ks + 32 * i, ks + 32 * i + 32);
  std::vector<Aff<Fp>> apts(n_pts);
  for (uint64_t m = 0; m < n_msms; ++m) {
    for (uint64_t i = 0; i < n_pts; i++)
      apts[i] = g1_from_wire(pts + (m * n_pts + i) * 96);
    g1_to_wire(jac_to_aff(msm(apts, scalars)), out + m * 96);
  }
}

// Evaluate a G2-coefficient polynomial (a threshold public-key
// commitment) at the consecutive points x = 1..n — the key-dealing /
// DKG shape where every validator index needs its public key share.
// Strategy: the caller supplies scalar power rows for the first
// m = min(ncoeffs, n) points (direct MSMs); the remaining n−m values
// come from the forward-difference recurrence — for a degree-t
// polynomial the (t+1)-th difference vanishes, so each further point
// is t group additions and no scalar multiplications at all.
void hb_g2_poly_eval_range(uint64_t ncoeffs, const uint8_t* coeffs,
                           uint64_t n, const uint8_t* powmat,
                           uint8_t* out) {
  std::vector<Aff<Fp2>> apts(ncoeffs);
  for (uint64_t j = 0; j < ncoeffs; j++)
    apts[j] = g2_from_wire(coeffs + 192 * j);
  uint64_t m = ncoeffs < n ? ncoeffs : n;
  std::vector<Jac<Fp2>> d(m);
  for (uint64_t i = 0; i < m; i++) {
    std::vector<std::vector<uint8_t>> ks(ncoeffs);
    for (uint64_t j = 0; j < ncoeffs; j++)
      ks[j].assign(powmat + (i * ncoeffs + j) * 32,
                   powmat + (i * ncoeffs + j) * 32 + 32);
    d[i] = msm(apts, ks);
    g2_to_wire(jac_to_aff(d[i]), out + 192 * i);
  }
  if (n <= m) return;
  // difference pyramid: d[k] := Δᵏf(1)
  for (uint64_t k = 1; k < m; k++)
    for (uint64_t i = m - 1; i >= k; i--) {
      Jac<Fp2> neg = {d[i - 1].X, fp2_neg(d[i - 1].Y), d[i - 1].Z};
      d[i] = jac_add(d[i], neg);
      if (i == k) break;
    }
  // advance the state one point per step; from step >= m the head is a
  // fresh value f(step+1)
  for (uint64_t step = 1; step < n; step++) {
    for (uint64_t k = 0; k + 1 < m; k++) d[k] = jac_add(d[k], d[k + 1]);
    if (step >= m) g2_to_wire(jac_to_aff(d[0]), out + 192 * step);
  }
}

// out[n*m] = a[n*k] · b[k*m] over Fr — every entry a 32-byte
// big-endian scalar mod r.  The DKG dealing/value-grid workhorse
// (sync_key_gen.rs:268-299): row coefficients for all receivers are
// POW·C_d, value grids are ROWS·POWᵀ — at N=256 that is ~10⁹
// Montgomery multiplications, native-only territory.
void hb_fr_matmul(uint64_t n, uint64_t k, uint64_t m, const uint8_t* a,
                  const uint8_t* b, uint8_t* out) {
  std::vector<Fr> am(n * k), bm(k * m);
  for (uint64_t i = 0; i < n * k; i++)
    am[i] = fr_mont_mul(fr_from_be(a + 32 * i), FR_R2);
  for (uint64_t i = 0; i < k * m; i++)
    bm[i] = fr_mont_mul(fr_from_be(b + 32 * i), FR_R2);
  for (uint64_t i = 0; i < n; i++) {
    for (uint64_t j = 0; j < m; j++) {
      Fr acc = {{0, 0, 0, 0}};
      const Fr* arow = &am[i * k];
      for (uint64_t l = 0; l < k; l++)
        acc = fr_add(acc, fr_mont_mul(arow[l], bm[l * m + j]));
      acc = fr_mont_mul(acc, FR_ONE_PLAIN);  // leave Montgomery form
      fr_to_be(acc, out + 32 * (i * m + j));
    }
  }
}

// Many scalar-muls of ONE shared G2 base — the DKG dealing shape
// (every commitment entry is coeff·P₂, sync_key_gen.rs:268-299).
// Same 8-bit fixed-base comb as hb_g1_mul_many, over Fq².
void hb_g2_mul_many(uint64_t n, const uint8_t* p, const uint8_t* ks,
                    uint8_t* out) {
  comb_mul_many<Fp2, 192, g2_from_wire, g2_to_wire>(n, p, ks, out);
}

// Π e(Pᵢ, Qᵢ) == 1 ?  (one shared final exponentiation)
int hb_pairing_check(uint64_t n, const uint8_t* g1s, const uint8_t* g2s) {
  Fp12 acc = FP12_ONE;
  for (uint64_t i = 0; i < n; i++) {
    Aff<Fp> p = g1_from_wire(g1s + 96 * i);
    Aff<Fp2> q = g2_from_wire(g2s + 192 * i);
    acc = fp12_mul(acc, miller_loop(p, q));
  }
  return fp12_eq(final_exponentiation(acc), FP12_ONE) ? 1 : 0;
}

// e(P, Q)³ — canonical pairing value, byte-identical to the Python oracle
void hb_pairing(const uint8_t* p, const uint8_t* q, uint8_t* out) {
  Aff<Fp> pa = g1_from_wire(p);
  Aff<Fp2> qa = g2_from_wire(q);
  fp12_to_wire(final_exponentiation(miller_loop(pa, qa)), out);
}

// try-and-increment hash to the G1 subgroup, matching
// hbbft_tpu/crypto/hashing.py::hash_to_g1 byte-for-byte.
void hb_hash_to_g1(const uint8_t* msg, uint64_t msg_len, const uint8_t* dst,
                   uint64_t dst_len, uint8_t* out) {
  std::vector<uint8_t> buf(dst_len + 1 + msg_len + 1);
  memcpy(buf.data(), dst, dst_len);
  buf[dst_len] = (uint8_t)dst_len;
  memcpy(buf.data() + dst_len + 1, msg, msg_len);
  for (int ctr = 0; ctr < 256; ctr++) {
    buf[buf.size() - 1] = (uint8_t)ctr;
    uint8_t digest[64];
    sha512(buf.data(), buf.size(), digest);
    uint64_t xplain[6];
    reduce512_mod_p(digest, xplain);
    Fp x;
    {
      Fp tmp;
      for (int i = 0; i < 6; i++) tmp.l[i] = xplain[i];
      x = fp_mul(tmp, R2);
    }
    // y² = x³ + 4
    Fp four = fp_dbl(fp_dbl(FP_ONE));
    Fp rhs = fp_add(fp_mul(fp_sq(x), x), four);
    Fp y;
    if (!fp_sqrt(rhs, &y)) continue;
    Fp ny = fp_neg(y);
    if (fp_std_less(ny, y)) y = ny;  // canonical smaller root
    // clear cofactor
    Jac<Fp> pt = {x, y, FP_ONE};
    Jac<Fp> cleared = jac_mul_limbs(pt, H1_LIMBS, 2);
    if (cleared.is_inf()) continue;
    g1_to_wire(jac_to_aff(cleared), out);
    return;
  }
  memset(out, 0, 96);  // unreachable (probability ~2^-256)
}

}  // extern "C"
