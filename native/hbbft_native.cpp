// hbbft_tpu native host library.
//
// Host-side fast paths for the three native dependencies of the
// reference (SURVEY.md §2.4): `ring` SHA-256 (broadcast.rs:161),
// the `merkle` crate (broadcast.rs:381-392), and
// `reed-solomon-erasure` (broadcast.rs:365, :643-656).  The TPU
// kernels in hbbft_tpu/ops/ are the device path; this library is the
// native host path used by the CPU reference backend so the
// correctness oracle itself runs at native speed.
//
// Exposed as a plain C ABI consumed via ctypes
// (hbbft_tpu/native/__init__.py).  Semantics are bit-identical to the
// pure-Python implementations in hbbft_tpu/crypto/{hashing,merkle,rs}.py
// — the bit-identity tests in tests/test_native.py enforce this.

#include <cstdint>
#include <cstring>
#include <vector>

#if defined(__x86_64__)
#include <cpuid.h>
#include <immintrin.h>
#endif

// ---------------------------------------------------------------------------
// SHA-256 (FIPS 180-4)
// ---------------------------------------------------------------------------

namespace {

constexpr uint32_t K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

inline uint32_t rotr(uint32_t x, int n) { return (x >> n) | (x << (32 - n)); }

#if defined(__x86_64__)
// SHA-NI one-block-at-a-time compression (x86 SHA extensions).  This
// is what makes the native Merkle/hash path beat OpenSSL-backed
// hashlib: same hardware instructions, no per-call Python overhead.
__attribute__((target("sha,sse4.1,ssse3"))) void sha256_compress_shani(
    uint32_t s[8], const uint8_t* data, size_t nblk) {
  const __m128i MASK =
      _mm_set_epi64x(0x0c0d0e0f08090a0bULL, 0x0405060700010203ULL);
  __m128i TMP = _mm_loadu_si128(reinterpret_cast<const __m128i*>(&s[0]));
  __m128i STATE1 = _mm_loadu_si128(reinterpret_cast<const __m128i*>(&s[4]));
  TMP = _mm_shuffle_epi32(TMP, 0xB1);
  STATE1 = _mm_shuffle_epi32(STATE1, 0x1B);
  __m128i STATE0 = _mm_alignr_epi8(TMP, STATE1, 8);
  STATE1 = _mm_blend_epi16(STATE1, TMP, 0xF0);

  while (nblk--) {
    __m128i ABEF_SAVE = STATE0, CDGH_SAVE = STATE1;
    __m128i MSG, MSG0, MSG1, MSG2, MSG3;

    MSG0 = _mm_shuffle_epi8(
        _mm_loadu_si128(reinterpret_cast<const __m128i*>(data + 0)), MASK);
    MSG = _mm_add_epi32(
        MSG0, _mm_set_epi64x(0xE9B5DBA5B5C0FBCFULL, 0x71374491428A2F98ULL));
    STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
    MSG = _mm_shuffle_epi32(MSG, 0x0E);
    STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);

    MSG1 = _mm_shuffle_epi8(
        _mm_loadu_si128(reinterpret_cast<const __m128i*>(data + 16)), MASK);
    MSG = _mm_add_epi32(
        MSG1, _mm_set_epi64x(0xAB1C5ED5923F82A4ULL, 0x59F111F13956C25BULL));
    STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
    MSG = _mm_shuffle_epi32(MSG, 0x0E);
    STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);
    MSG0 = _mm_sha256msg1_epu32(MSG0, MSG1);

    MSG2 = _mm_shuffle_epi8(
        _mm_loadu_si128(reinterpret_cast<const __m128i*>(data + 32)), MASK);
    MSG = _mm_add_epi32(
        MSG2, _mm_set_epi64x(0x550C7DC3243185BEULL, 0x12835B01D807AA98ULL));
    STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
    MSG = _mm_shuffle_epi32(MSG, 0x0E);
    STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);
    MSG1 = _mm_sha256msg1_epu32(MSG1, MSG2);

    MSG3 = _mm_shuffle_epi8(
        _mm_loadu_si128(reinterpret_cast<const __m128i*>(data + 48)), MASK);
    MSG = _mm_add_epi32(
        MSG3, _mm_set_epi64x(0xC19BF1749BDC06A7ULL, 0x80DEB1FE72BE5D74ULL));
    STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
    MSG = _mm_shuffle_epi32(MSG, 0x0E);
    STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);
    TMP = _mm_alignr_epi8(MSG3, MSG2, 4);
    MSG0 = _mm_add_epi32(MSG0, TMP);
    MSG0 = _mm_sha256msg2_epu32(MSG0, MSG3);
    MSG2 = _mm_sha256msg1_epu32(MSG2, MSG3);

    for (int i = 16; i < 64; i += 4) {
      MSG = _mm_add_epi32(MSG0,
                          _mm_set_epi32(int(K[i + 3]), int(K[i + 2]),
                                        int(K[i + 1]), int(K[i])));
      STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
      MSG = _mm_shuffle_epi32(MSG, 0x0E);
      STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);
      TMP = _mm_alignr_epi8(MSG0, MSG3, 4);
      MSG1 = _mm_add_epi32(MSG1, TMP);
      MSG1 = _mm_sha256msg2_epu32(MSG1, MSG0);
      MSG3 = _mm_sha256msg1_epu32(MSG3, MSG0);
      __m128i rot = MSG0;
      MSG0 = MSG1;
      MSG1 = MSG2;
      MSG2 = MSG3;
      MSG3 = rot;
    }

    STATE0 = _mm_add_epi32(STATE0, ABEF_SAVE);
    STATE1 = _mm_add_epi32(STATE1, CDGH_SAVE);
    data += 64;
  }

  TMP = _mm_shuffle_epi32(STATE0, 0x1B);
  STATE1 = _mm_shuffle_epi32(STATE1, 0xB1);
  STATE0 = _mm_blend_epi16(TMP, STATE1, 0xF0);
  STATE1 = _mm_alignr_epi8(STATE1, TMP, 8);
  _mm_storeu_si128(reinterpret_cast<__m128i*>(&s[0]), STATE0);
  _mm_storeu_si128(reinterpret_cast<__m128i*>(&s[4]), STATE1);
}

bool have_shani() {
  static const bool ok = [] {
    unsigned eax = 7, ebx, ecx = 0, edx;
    if (!__get_cpuid_count(7, 0, &eax, &ebx, &ecx, &edx)) return false;
    return (ebx & (1u << 29)) != 0;  // SHA bit
  }();
  return ok;
}
#else
bool have_shani() { return false; }
#endif

struct Sha256 {
  uint32_t h[8];
  uint8_t buf[64];
  uint64_t total;
  size_t fill;

  Sha256() { reset(); }

  void reset() {
    static const uint32_t init[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372,
                                     0xa54ff53a, 0x510e527f, 0x9b05688c,
                                     0x1f83d9ab, 0x5be0cd19};
    std::memcpy(h, init, sizeof(h));
    total = 0;
    fill = 0;
  }

  void compress(const uint8_t* p) {
    uint32_t w[64];
    for (int i = 0; i < 16; ++i)
      w[i] = (uint32_t(p[4 * i]) << 24) | (uint32_t(p[4 * i + 1]) << 16) |
             (uint32_t(p[4 * i + 2]) << 8) | uint32_t(p[4 * i + 3]);
    for (int i = 16; i < 64; ++i) {
      uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
      uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
      w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint32_t a = h[0], b = h[1], c = h[2], d = h[3];
    uint32_t e = h[4], f = h[5], g = h[6], hh = h[7];
    for (int i = 0; i < 64; ++i) {
      uint32_t S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
      uint32_t ch = (e & f) ^ (~e & g);
      uint32_t t1 = hh + S1 + ch + K[i] + w[i];
      uint32_t S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
      uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
      uint32_t t2 = S0 + maj;
      hh = g;
      g = f;
      f = e;
      e = d + t1;
      d = c;
      c = b;
      b = a;
      a = t1 + t2;
    }
    h[0] += a; h[1] += b; h[2] += c; h[3] += d;
    h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
  }

  void update(const uint8_t* data, size_t len) {
    total += len;
    if (fill) {
      size_t take = 64 - fill;
      if (take > len) take = len;
      std::memcpy(buf + fill, data, take);
      fill += take;
      data += take;
      len -= take;
      if (fill == 64) {
        compress_n(buf, 1);
        fill = 0;
      }
    }
    if (len >= 64) {
      size_t nblk = len / 64;
      compress_n(data, nblk);
      data += nblk * 64;
      len -= nblk * 64;
    }
    if (len) {
      std::memcpy(buf, data, len);
      fill = len;
    }
  }

  void compress_n(const uint8_t* data, size_t nblk) {
#if defined(__x86_64__)
    if (have_shani()) {
      sha256_compress_shani(h, data, nblk);
      return;
    }
#endif
    for (size_t i = 0; i < nblk; ++i) compress(data + 64 * i);
  }

  void final(uint8_t out[32]) {
    uint64_t bits = total * 8;
    uint8_t pad = 0x80;
    update(&pad, 1);
    uint8_t zero = 0;
    while (fill != 56) update(&zero, 1);
    uint8_t lenb[8];
    for (int i = 0; i < 8; ++i) lenb[i] = uint8_t(bits >> (56 - 8 * i));
    update(lenb, 8);
    for (int i = 0; i < 8; ++i) {
      out[4 * i] = uint8_t(h[i] >> 24);
      out[4 * i + 1] = uint8_t(h[i] >> 16);
      out[4 * i + 2] = uint8_t(h[i] >> 8);
      out[4 * i + 3] = uint8_t(h[i]);
    }
  }
};

void sha256_one(const uint8_t* data, size_t len, uint8_t out[32]) {
  Sha256 s;
  s.update(data, len);
  s.final(out);
}

}  // namespace

extern "C" {

// Hash `count` messages stored concatenated in `data`; message i spans
// [offsets[i], offsets[i+1]).  Writes 32*count bytes to `out`.
void hb_sha256_many(const uint8_t* data, const uint64_t* offsets,
                    uint64_t count, uint8_t* out) {
  for (uint64_t i = 0; i < count; ++i) {
    sha256_one(data + offsets[i], size_t(offsets[i + 1] - offsets[i]),
               out + 32 * i);
  }
}

// ---------------------------------------------------------------------------
// Merkle tree (matches hbbft_tpu/crypto/merkle.py exactly):
//   leaf  = SHA256(0x00 || index_be64 || value)
//   node  = SHA256(0x01 || left || right)
//   odd levels duplicate the trailing hash before pairing.
// ---------------------------------------------------------------------------

// Total number of 32-byte hashes across all levels, including
// duplicated trailing hashes (so Python can pre-allocate and split).
uint64_t hb_merkle_total_hashes(uint64_t n) {
  uint64_t total = 0;
  uint64_t len = n;
  for (;;) {
    if (len > 1 && (len & 1)) len += 1;
    total += len;
    if (len <= 1) break;
    len /= 2;
  }
  return total;
}

// Build the full tree.  Leaves are concatenated in `data` with
// `offsets` (n+1 entries).  `out` receives every level's hashes
// back-to-back, bottom level (after odd-duplication) first.
void hb_merkle_build(const uint8_t* data, const uint64_t* offsets,
                     uint64_t n, uint8_t* out) {
  uint8_t* level = out;
  // leaf level
  for (uint64_t i = 0; i < n; ++i) {
    Sha256 s;
    uint8_t prefix[9];
    prefix[0] = 0x00;
    for (int b = 0; b < 8; ++b) prefix[1 + b] = uint8_t(i >> (56 - 8 * b));
    s.update(prefix, 9);
    s.update(data + offsets[i], size_t(offsets[i + 1] - offsets[i]));
    s.final(level + 32 * i);
  }
  uint64_t len = n;
  for (;;) {
    if (len > 1 && (len & 1)) {
      std::memcpy(level + 32 * len, level + 32 * (len - 1), 32);
      len += 1;
    }
    if (len <= 1) break;
    uint8_t* next = level + 32 * len;
    for (uint64_t i = 0; i < len; i += 2) {
      Sha256 s;
      uint8_t prefix = 0x01;
      s.update(&prefix, 1);
      s.update(level + 32 * i, 64);
      s.final(next + 16 * i);  // 32 * (i/2)
    }
    level = next;
    len /= 2;
  }
}

// ---------------------------------------------------------------------------
// GF(2^8) arithmetic + systematic Reed-Solomon
// (matches hbbft_tpu/crypto/rs.py: primitive polynomial 0x11d).
// ---------------------------------------------------------------------------

namespace {

uint8_t GF_EXP[512];
int32_t GF_LOG[256];
uint8_t GF_MUL[256][256];

struct GfInit {
  GfInit() {
    int x = 1;
    for (int i = 0; i < 255; ++i) {
      GF_EXP[i] = uint8_t(x);
      GF_LOG[x] = i;
      x <<= 1;
      if (x & 0x100) x ^= 0x11d;
    }
    for (int i = 255; i < 512; ++i) GF_EXP[i] = GF_EXP[i - 255];
    GF_LOG[0] = 0;
    for (int a = 0; a < 256; ++a)
      for (int b = 0; b < 256; ++b)
        GF_MUL[a][b] = (a && b) ? GF_EXP[GF_LOG[a] + GF_LOG[b]] : 0;
  }
} gf_init_once;

inline uint8_t gf_mul(uint8_t a, uint8_t b) { return GF_MUL[a][b]; }

inline uint8_t gf_inv(uint8_t a) { return GF_EXP[255 - GF_LOG[a]]; }

// out[r] ^= c * in[r]  over a row of `len` bytes — the RS inner loop.
inline void gf_mul_xor_row_scalar(uint8_t* out, const uint8_t* in, uint8_t c,
                                  uint64_t len) {
  const uint8_t* mul = GF_MUL[c];
  for (uint64_t i = 0; i < len; ++i) out[i] ^= mul[in[i]];
}

#if defined(__x86_64__)
// AVX2 nibble-table variant (the ISA-L / PSHUFB technique): GF(2^8)
// multiplication is GF(2)-linear, so c·x = c·(x & 0x0f) ⊕ c·(x & 0xf0);
// two 16-entry VPSHUFB lookups process 32 bytes per iteration.  Tables
// come straight from the GF_MUL row, so this works for our 0x11d
// polynomial (GFNI's fixed-poly multiply would not).
__attribute__((target("avx2"))) static void gf_mul_xor_row_avx2(
    uint8_t* out, const uint8_t* in, uint8_t c, uint64_t len) {
  const uint8_t* mul = GF_MUL[c];
  alignas(32) uint8_t lo[16], hi[16];
  for (int i = 0; i < 16; ++i) {
    lo[i] = mul[i];
    hi[i] = mul[i << 4];
  }
  const __m256i vlo =
      _mm256_broadcastsi128_si256(_mm_load_si128((const __m128i*)lo));
  const __m256i vhi =
      _mm256_broadcastsi128_si256(_mm_load_si128((const __m128i*)hi));
  const __m256i nib = _mm256_set1_epi8(0x0f);
  uint64_t i = 0;
  for (; i + 32 <= len; i += 32) {
    __m256i x = _mm256_loadu_si256((const __m256i*)(in + i));
    __m256i l = _mm256_shuffle_epi8(vlo, _mm256_and_si256(x, nib));
    __m256i h = _mm256_shuffle_epi8(
        vhi, _mm256_and_si256(_mm256_srli_epi16(x, 4), nib));
    __m256i o = _mm256_loadu_si256((const __m256i*)(out + i));
    _mm256_storeu_si256(
        (__m256i*)(out + i),
        _mm256_xor_si256(o, _mm256_xor_si256(l, h)));
  }
  for (; i < len; ++i) out[i] ^= mul[in[i]];
}

static bool cpu_has_avx2() {
  __builtin_cpu_init();
  return __builtin_cpu_supports("avx2");
}
static const bool HAS_AVX2 = cpu_has_avx2();
#endif

inline void gf_mul_xor_row(uint8_t* out, const uint8_t* in, uint8_t c,
                           uint64_t len) {
#if defined(__x86_64__)
  if (HAS_AVX2) {
    gf_mul_xor_row_avx2(out, in, c, len);
    return;
  }
#endif
  gf_mul_xor_row_scalar(out, in, c, len);
}

}  // namespace

// C = A(m×k) · B(k×n) over GF(2^8).
void hb_gf_matmul(const uint8_t* a, const uint8_t* b, uint8_t* c, int m,
                  int k, int n) {
  std::memset(c, 0, size_t(m) * n);
  for (int i = 0; i < m; ++i)
    for (int j = 0; j < k; ++j) {
      uint8_t aij = a[i * k + j];
      if (aij) gf_mul_xor_row(c + size_t(i) * n, b + size_t(j) * n, aij, n);
    }
}

// Gauss-Jordan inverse over GF(2^8).  Returns 0 on success, -1 if
// singular.  `m` is n×n row-major; `out` receives the inverse.
int hb_gf_mat_inv(const uint8_t* m, uint8_t* out, int n) {
  std::vector<uint8_t> aug(size_t(n) * 2 * n, 0);
  for (int i = 0; i < n; ++i) {
    std::memcpy(&aug[size_t(i) * 2 * n], m + size_t(i) * n, n);
    aug[size_t(i) * 2 * n + n + i] = 1;
  }
  int w = 2 * n;
  for (int col = 0; col < n; ++col) {
    int pivot = -1;
    for (int row = col; row < n; ++row)
      if (aug[size_t(row) * w + col]) {
        pivot = row;
        break;
      }
    if (pivot < 0) return -1;
    if (pivot != col)
      for (int j = 0; j < w; ++j)
        std::swap(aug[size_t(col) * w + j], aug[size_t(pivot) * w + j]);
    uint8_t inv_p = gf_inv(aug[size_t(col) * w + col]);
    for (int j = 0; j < w; ++j)
      aug[size_t(col) * w + j] = gf_mul(aug[size_t(col) * w + j], inv_p);
    for (int row = 0; row < n; ++row) {
      if (row == col) continue;
      uint8_t factor = aug[size_t(row) * w + col];
      if (!factor) continue;
      const uint8_t* mul = GF_MUL[factor];
      for (int j = 0; j < w; ++j)
        aug[size_t(row) * w + j] ^= mul[aug[size_t(col) * w + j]];
    }
  }
  for (int i = 0; i < n; ++i)
    std::memcpy(out + size_t(i) * n, &aug[size_t(i) * w + n], n);
  return 0;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// GF(2^16) Reed-Solomon kernels — the >256-shard (N=1024 validator)
// broadcast path.  Same design as the GF(2^8) kernels above: log/exp
// tables for scalars, a per-coefficient nibble-table row kernel
// (4 input nibbles x lo/hi product bytes = 8 VPSHUFB lookups per 16
// symbols) for the payload matmuls.  Polynomial 0x1100B, generator 3
// (must match hbbft_tpu/crypto/rs.py).
// ---------------------------------------------------------------------------

namespace {

uint16_t* GF16_EXP = nullptr;  // [2*65535]
int32_t* GF16_LOG = nullptr;   // [65536]

struct Gf16Init {
  Gf16Init() {
    GF16_EXP = new uint16_t[2 * 65535];
    GF16_LOG = new int32_t[65536];
    int x = 1;
    for (int i = 0; i < 65535; ++i) {
      GF16_EXP[i] = uint16_t(x);
      GF16_LOG[x] = i;
      x <<= 1;
      if (x & 0x10000) x ^= 0x1100B;
    }
    for (int i = 65535; i < 2 * 65535; ++i) GF16_EXP[i] = GF16_EXP[i - 65535];
    GF16_LOG[0] = 0;
  }
} gf16_init_once;

inline uint16_t gf16_mul(uint16_t a, uint16_t b) {
  if (!a || !b) return 0;
  return GF16_EXP[GF16_LOG[a] + GF16_LOG[b]];
}

inline uint16_t gf16_inv(uint16_t a) { return GF16_EXP[65535 - GF16_LOG[a]]; }

// Per-coefficient nibble tables: c*x = XOR_j c*(nib_j(x) << 4j).
struct Gf16Tables {
  // tab[j][e] = c * (e << (4*j)), split into lo/hi bytes for PSHUFB
  alignas(32) uint8_t lo[4][16];
  alignas(32) uint8_t hi[4][16];
  uint16_t full[4][16];
  void build(uint16_t c) {
    for (int j = 0; j < 4; ++j)
      for (int e = 0; e < 16; ++e) {
        uint16_t p = gf16_mul(c, uint16_t(e << (4 * j)));
        full[j][e] = p;
        lo[j][e] = uint8_t(p & 0xff);
        hi[j][e] = uint8_t(p >> 8);
      }
  }
};

inline void gf16_mul_xor_row_scalar(uint16_t* out, const uint16_t* in,
                                    const Gf16Tables& t, uint64_t len) {
  for (uint64_t i = 0; i < len; ++i) {
    uint16_t x = in[i];
    out[i] ^= t.full[0][x & 0xf] ^ t.full[1][(x >> 4) & 0xf] ^
              t.full[2][(x >> 8) & 0xf] ^ t.full[3][(x >> 12) & 0xf];
  }
}

#if defined(__x86_64__)
__attribute__((target("avx2"))) static void gf16_mul_xor_row_avx2(
    uint16_t* out, const uint16_t* in, const Gf16Tables& t, uint64_t len) {
  const __m256i nib = _mm256_set1_epi16(0x000f);
  const __m256i lobyte = _mm256_set1_epi16(0x00ff);
  __m256i vlo[4], vhi[4];
  for (int j = 0; j < 4; ++j) {
    vlo[j] = _mm256_broadcastsi128_si256(_mm_load_si128((const __m128i*)t.lo[j]));
    vhi[j] = _mm256_broadcastsi128_si256(_mm_load_si128((const __m128i*)t.hi[j]));
  }
  uint64_t i = 0;
  for (; i + 16 <= len; i += 16) {
    __m256i x = _mm256_loadu_si256((const __m256i*)(in + i));
    __m256i acc = _mm256_setzero_si256();
    for (int j = 0; j < 4; ++j) {
      __m256i n = _mm256_and_si256(_mm256_srli_epi16(x, 4 * j), nib);
      // replicate the nibble index into both bytes of each 16-bit lane
      __m256i idx = _mm256_or_si256(n, _mm256_slli_epi16(n, 8));
      __m256i pl = _mm256_and_si256(_mm256_shuffle_epi8(vlo[j], idx), lobyte);
      __m256i ph = _mm256_slli_epi16(
          _mm256_and_si256(_mm256_shuffle_epi8(vhi[j], idx), lobyte), 8);
      acc = _mm256_xor_si256(acc, _mm256_or_si256(pl, ph));
    }
    __m256i o = _mm256_loadu_si256((const __m256i*)(out + i));
    _mm256_storeu_si256((__m256i*)(out + i), _mm256_xor_si256(o, acc));
  }
  if (i < len) gf16_mul_xor_row_scalar(out + i, in + i, t, len - i);
}
#endif

inline void gf16_mul_xor_row(uint16_t* out, const uint16_t* in,
                             const Gf16Tables& t, uint64_t len) {
#if defined(__x86_64__)
  if (HAS_AVX2) {
    gf16_mul_xor_row_avx2(out, in, t, len);
    return;
  }
#endif
  gf16_mul_xor_row_scalar(out, in, t, len);
}

}  // namespace

extern "C" {

// C = A(m x k) . B(k x n) over GF(2^16); all row-major uint16.
void hb_gf16_matmul(const uint16_t* a, const uint16_t* b, uint16_t* c, int m,
                    int k, int n) {
  std::memset(c, 0, size_t(m) * n * 2);
  Gf16Tables t;
  for (int i = 0; i < m; ++i)
    for (int j = 0; j < k; ++j) {
      uint16_t aij = a[size_t(i) * k + j];
      if (!aij) continue;
      t.build(aij);
      gf16_mul_xor_row(c + size_t(i) * n, b + size_t(j) * n, t, n);
    }
}

// Gauss-Jordan inverse over GF(2^16); 0 on success, -1 if singular.
int hb_gf16_mat_inv(const uint16_t* m, uint16_t* out, int n) {
  std::vector<uint16_t> aug(size_t(n) * 2 * n, 0);
  for (int i = 0; i < n; ++i) {
    std::memcpy(&aug[size_t(i) * 2 * n], m + size_t(i) * n, size_t(n) * 2);
    aug[size_t(i) * 2 * n + n + i] = 1;
  }
  int w = 2 * n;
  for (int col = 0; col < n; ++col) {
    int pivot = -1;
    for (int row = col; row < n; ++row)
      if (aug[size_t(row) * w + col]) {
        pivot = row;
        break;
      }
    if (pivot < 0) return -1;
    if (pivot != col)
      for (int j = 0; j < w; ++j)
        std::swap(aug[size_t(col) * w + j], aug[size_t(pivot) * w + j]);
    uint16_t inv_p = gf16_inv(aug[size_t(col) * w + col]);
    for (int j = 0; j < w; ++j)
      aug[size_t(col) * w + j] = gf16_mul(aug[size_t(col) * w + j], inv_p);
    for (int row = 0; row < n; ++row) {
      if (row == col) continue;
      uint16_t factor = aug[size_t(row) * w + col];
      if (!factor) continue;
      Gf16Tables t;
      t.build(factor);
      gf16_mul_xor_row(&aug[size_t(row) * w], &aug[size_t(col) * w], t, w);
    }
  }
  for (int i = 0; i < n; ++i)
    std::memcpy(out + size_t(i) * n, &aug[size_t(i) * w + n], size_t(n) * 2);
  return 0;
}

}  // extern "C"
