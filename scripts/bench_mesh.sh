#!/usr/bin/env bash
# Mesh scaling bench: run the mesh headline (`bench.py --mesh`) at
# 1/2/4/8 virtual devices and emit a JSON scaling table for the
# scenario/obs plane.  Each device count runs the REAL flush path
# (BatchingBackend product-MSM sharded over parallel/mesh.py) in its
# own child process — a JAX backend's device count is fixed once
# initialized, so only fresh interpreters can host each mesh width.
#
# Examples:
#   scripts/bench_mesh.sh                       # 1,2,4,8 devices, k=512
#   MESH_K=8192 scripts/bench_mesh.sh           # bigger flush shape
#   MESH_DEVICES=1,8 MESH_ITERS=5 scripts/bench_mesh.sh
#   MESH_OUT=mesh_scaling.json scripts/bench_mesh.sh  # also write a file
#
# Output: the per-device-count `share_verify_throughput` rows (one
# JSON line each, `mesh_devices` tagged) followed by one
# `mesh_share_verify_scaling` summary row.  With MESH_OUT set, all
# rows are also collected into a single JSON array at that path.
set -uo pipefail
cd "$(dirname "$0")/.."

k="${MESH_K:-512}"
devices="${MESH_DEVICES:-1,2,4,8}"
iters="${MESH_ITERS:-3}"
out="${MESH_OUT:-}"

log="$(mktemp)"
trap 'rm -f "$log"' EXIT

python bench.py --mesh --k "$k" --mesh-devices "$devices" \
  --iters "$iters" 2>&1 | tee "$log"
rc=${PIPESTATUS[0]}

if [ -n "$out" ] && [ "$rc" = 0 ]; then
  # collect the JSON rows into one array file for downstream tooling
  python - "$log" "$out" <<'PY'
import json, sys

rows = []
with open(sys.argv[1]) as fh:
    for line in fh:
        line = line.strip()
        if line.startswith("{"):
            try:
                rows.append(json.loads(line))
            except ValueError:
                pass
with open(sys.argv[2], "w") as fh:
    json.dump(rows, fh, indent=2)
print("wrote %d rows to %s" % (len(rows), sys.argv[2]))
PY
fi

exit "$rc"
