#!/usr/bin/env bash
# Cold-start pair: time the FIRST flush of a fresh process twice
# against the SAME `.palexe` exec-cache directory —
#
#   run 1  VIRGIN cache + HBBFT_TPU_WARM=1: pays every compile and
#          serializes the planned executables to disk
#   run 2  PRIMED cache, warming OFF: the prewarm plan must preload
#          everything, and the flush must log ZERO compile events
#
# Each run is its own interpreter (`bench.py --cold`) because a
# process only ever has one first flush.  Both runs force the device
# leg (G1_DEVICE_MIN=1, HBBFT_TPU_DEVICE_FRACTION=1) so the row
# measures the device path's cold wall, not the host fallback, and
# run under HBBFT_TPU_AOT=1 so the CPU host exercises the same
# exec-cache machinery a TPU host does.
#
# Examples:
#   scripts/bench_cold.sh                     # k=4096, tmp cache dir
#   COLD_K=8192 scripts/bench_cold.sh
#   COLD_CACHE=/var/cache/hbbft scripts/bench_cold.sh  # keep the cache
#
# Output: the two `cold_flush` JSON rows, then one `cold_prime_ratio`
# summary row (virgin wall ÷ primed wall) with the primed run's
# compile-event count — nonzero means the prewarm plan has a hole.
set -uo pipefail
cd "$(dirname "$0")/.."

k="${COLD_K:-4096}"
cache="${COLD_CACHE:-}"
keep_cache=1
if [ -z "$cache" ]; then
  cache="$(mktemp -d)"
  keep_cache=0
fi

log1="$(mktemp)"; log2="$(mktemp)"
cleanup() {
  rm -f "$log1" "$log2"
  [ "$keep_cache" = 0 ] && rm -rf "$cache"
}
trap cleanup EXIT

common_env=(
  JAX_PLATFORMS=cpu
  HBBFT_TPU_AOT=1
  HBBFT_TPU_EXEC_CACHE="$cache"
  HBBFT_TPU_DEVICE_FRACTION=1
)

echo "# run 1: virgin cache (compiles + serializes)" >&2
env "${common_env[@]}" HBBFT_TPU_WARM=1 \
  python bench.py --cold --k "$k" 2>&1 | tee "$log1"
rc1=${PIPESTATUS[0]}

echo "# run 2: primed cache (prewarm preloads; zero compiles expected)" >&2
env "${common_env[@]}" HBBFT_TPU_WARM=0 \
  python bench.py --cold --k "$k" 2>&1 | tee "$log2"
rc2=${PIPESTATUS[0]}

[ "$rc1" = 0 ] && [ "$rc2" = 0 ] || exit 1

python - "$log1" "$log2" <<'PY'
import json, sys

def row(path):
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if line.startswith("{"):
                r = json.loads(line)
                if r.get("metric") == "cold_flush":
                    return r
    raise SystemExit("no cold_flush row in %s" % path)

virgin, primed = row(sys.argv[1]), row(sys.argv[2])
summary = {
    "metric": "cold_prime_ratio",
    "value": round(virgin["value"] / max(primed["value"], 1e-9), 2),
    "unit": "x",
    "virgin_s": virgin["value"],
    "primed_s": primed["value"],
    "virgin_compiles": virgin.get("compile_events"),
    "primed_compiles": primed.get("compile_events"),
}
print(json.dumps(summary))
if primed.get("compile_events"):
    raise SystemExit(
        "FAIL: primed run still compiled %d program(s) — the prewarm "
        "plan has a hole" % primed["compile_events"]
    )
PY
