#!/usr/bin/env bash
# badgerlint wrapper: lint the package (or the given paths), forwarding
# all flags to the CLI.  Examples:
#   scripts/lint.sh
#   scripts/lint.sh --json
#   scripts/lint.sh --select determinism,layering hbbft_tpu/protocols
#   scripts/lint.sh --select thread-shared-state,lock-order,atomic-cache
#   scripts/lint.sh --racecheck tests/test_racecheck.py   # runtime lockset checker
#   scripts/lint.sh --changed            # only files in git diff (pre-commit)
#   LINT_LOG=/tmp/lint.log scripts/lint.sh
set -uo pipefail
cd "$(dirname "$0")/.."

changed=0
args=()
for a in "$@"; do
  if [ "$a" = "--changed" ]; then
    changed=1
  else
    args+=("$a")
  fi
done

targets=()
if [ "$changed" = 1 ]; then
  # staged + unstaged python files still on disk
  while IFS= read -r f; do
    [ -f "$f" ] && targets+=("$f")
  done < <(
    { git diff --name-only HEAD -- '*.py'
      git diff --cached --name-only -- '*.py'; } | sort -u
  )
  if [ "${#targets[@]}" -eq 0 ]; then
    echo "lint: no changed python files"
    exit 0
  fi
fi

# Under pipefail, ${PIPESTATUS[0]} is the lint's own exit code even
# when the output is piped through tee — the old `exec` form lost it
# as soon as a log pipe was added.
if [ -n "${LINT_LOG:-}" ]; then
  python -m hbbft_tpu.analysis "${args[@]+"${args[@]}"}" \
    "${targets[@]+"${targets[@]}"}" 2>&1 | tee "$LINT_LOG"
  exit "${PIPESTATUS[0]}"
fi
python -m hbbft_tpu.analysis "${args[@]+"${args[@]}"}" \
  "${targets[@]+"${targets[@]}"}"
exit $?
