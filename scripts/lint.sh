#!/usr/bin/env bash
# badgerlint wrapper: lint the package (or the given paths), forwarding
# all flags to the CLI.  Examples:
#   scripts/lint.sh
#   scripts/lint.sh --json
#   scripts/lint.sh --select determinism,layering hbbft_tpu/protocols
set -euo pipefail
cd "$(dirname "$0")/.."
exec python -m hbbft_tpu.analysis "$@"
