#!/usr/bin/env bash
# badgerlint wrapper: lint the package (or the given paths), forwarding
# all flags to the CLI.  Examples:
#   scripts/lint.sh
#   scripts/lint.sh --json
#   scripts/lint.sh --select determinism,layering hbbft_tpu/protocols
#   scripts/lint.sh --select thread-shared-state,lock-order,atomic-cache
#   scripts/lint.sh --select async-blocking,task-leak,await-holding-lock,cancellation-safety
#   scripts/lint.sh --racecheck tests/test_racecheck.py   # runtime lockset checker
#   scripts/lint.sh --stallcheck tests/ --stall-budget 0.25   # event-loop stall sanitizer
#   scripts/lint.sh --select limb-range      # limbprove: re-prove kernel ranges
#                                            # against range_manifest.json
#   scripts/lint.sh --write-range-manifest   # re-pin the proved range bounds
#   scripts/lint.sh --rangecheck tests/test_fr_jax.py   # exact-shadow overflow sanitizer
#   scripts/lint.sh --changed            # git-diff scope (pre-commit);
#                                        # the CLI widens to a full run when
#                                        # a changed file is in a
#                                        # whole-project rule's domain
#   LINT_LOG=/tmp/lint.log scripts/lint.sh
set -uo pipefail
cd "$(dirname "$0")/.."

# --changed used to be resolved here with git; it now lives in the CLI
# so the whole-project widening logic has one home.

# Under pipefail, ${PIPESTATUS[0]} is the lint's own exit code even
# when the output is piped through tee — the old `exec` form lost it
# as soon as a log pipe was added.
if [ -n "${LINT_LOG:-}" ]; then
  python -m hbbft_tpu.analysis "$@" 2>&1 | tee "$LINT_LOG"
  exit "${PIPESTATUS[0]}"
fi
python -m hbbft_tpu.analysis "$@"
exit $?
