#!/usr/bin/env bash
# Serving-gateway bench: run `bench.py --serve` — concurrent clients
# over a real n=4 TCP validator mesh through the gateway (admission,
# weighted-fair batching, gossip, consensus, commit acks).  Headline
# rows: `serve_tx_per_s` (sustained committed tx/s with exactly-once
# acks) and `serve_commit_latency` (client-observed p50/p99).  With
# SERVE_VECTOR=1, also run `bench.py --serve-vector` — BASELINE
# config #5 (n=1024, adversarial, 100 epochs) behind the same gateway
# core fed by synthetic million-client tenant arrival processes.
#
# Examples:
#   scripts/bench_serve.sh                     # 5 s TCP headline
#   SERVE_DURATION=10 scripts/bench_serve.sh   # longer sample
#   SERVE_VECTOR=1 scripts/bench_serve.sh      # + the n=1024 leg
#   SERVE_OUT=serve.json scripts/bench_serve.sh  # also write a file
set -uo pipefail
cd "$(dirname "$0")/.."

duration="${SERVE_DURATION:-5}"
out="${SERVE_OUT:-}"

log="$(mktemp)"
trap 'rm -f "$log"' EXIT

JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python bench.py --serve \
  --duration "$duration" 2>&1 | tee "$log"
rc=${PIPESTATUS[0]}

if [ "${SERVE_VECTOR:-0}" = 1 ] && [ "$rc" = 0 ]; then
  JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python bench.py --serve-vector \
    2>&1 | tee -a "$log"
  rc=${PIPESTATUS[0]}
fi

if [ -n "$out" ] && [ "$rc" = 0 ]; then
  python - "$log" "$out" <<'PY'
import json, sys

rows = []
with open(sys.argv[1]) as fh:
    for line in fh:
        line = line.strip()
        if line.startswith("{"):
            try:
                rows.append(json.loads(line))
            except ValueError:
                pass
with open(sys.argv[2], "w") as fh:
    json.dump(rows, fh, indent=2)
print("wrote %d rows to %s" % (len(rows), sys.argv[2]))
PY
fi

exit "$rc"
