#!/usr/bin/env bash
# Co-simulation scale sweep: `bench.py --cosim` — packed
# struct-of-arrays epochs at n ∈ {1k, 4k, 16k, 65k, 100k} under the
# WAN-real delay model (5 continental zones, lognormal tails, 2%
# crashed), preceded by the n=1024 byte-identity leg against the
# dict-based VectorizedQueueingSim.  One JSON line per row; all rows
# are also written to BENCH_COSIM_r0.json at the repo root.
#
# Examples:
#   scripts/bench_cosim.sh                           # full sweep
#   HBBFT_TPU_COSIM_NS=1000,16384 scripts/bench_cosim.sh
#   COSIM_EPOCHS=10 scripts/bench_cosim.sh           # longer warm leg
#   COSIM_OUT= scripts/bench_cosim.sh                # stdout only
#   HBBFT_TPU_COSIM_MESH=1 scripts/bench_cosim.sh    # force the mesh
#
# The sweep runs the mock-crypto protocol plane (the co-sim contract);
# single-host CPU numbers measure the packed engine, not a TPU pod.
set -uo pipefail
cd "$(dirname "$0")/.."

epochs="${COSIM_EPOCHS:-3}"
out="${COSIM_OUT-BENCH_COSIM_r0.json}"

exec python bench.py --cosim --epochs "$epochs" --cosim-out "$out"
