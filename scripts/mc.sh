#!/usr/bin/env bash
# badgermc — bounded schedule-space model checking of the protocol
# state machines (hbbft_tpu.analysis.modelcheck): DFS over every
# inequivalent message-delivery interleaving of an n=4 network up to a
# depth bound, with canonical state-hash dedup, sleep-set DPOR, and
# optional Byzantine choice points; safety invariants asserted at every
# state, violations ddmin-shrunk to a replayable counterexample.
#
# Without arguments runs the full clean matrix (every protocol stack at
# its pinned depth).  Any arguments are passed straight through to
# `python -m hbbft_tpu.analysis --mc`:
#
#   scripts/mc.sh                                        # clean matrix
#   scripts/mc.sh --mc-config agreement --mc-depth 5     # one stack
#   scripts/mc.sh --mc-config honey_badger --mc-depth 4 \
#                 --mc-corrupt 1 --mc-repro /tmp/cex.json
#   MC_TRACE=/tmp/mc.jsonl scripts/mc.sh                 # obs mc_run rows
#
# Replay a written counterexample with:
#   python -m hbbft_tpu.harness.scenarios --replay-trace /tmp/cex.json
set -uo pipefail
cd "$(dirname "$0")/.."

trace_args=()
if [ -n "${MC_TRACE:-}" ]; then
  trace_args=(--trace "$MC_TRACE")
fi

if [ "$#" -gt 0 ]; then
  exec env JAX_PLATFORMS=cpu python -m hbbft_tpu.analysis --mc \
    "${trace_args[@]}" "$@"
fi

# The pinned clean matrix: every stack, honest and corrupt=1, at depths
# that keep the whole sweep around two minutes on one CPU core.
rc=0
run() {
  echo "== badgermc $* =="
  env JAX_PLATFORMS=cpu python -m hbbft_tpu.analysis --mc \
    "${trace_args[@]}" "$@" || rc=1
}
run --mc-config sbv_broadcast --mc-depth 6 --mc-min-states 3000
run --mc-config common_coin   --mc-depth 6 --mc-min-states 5000
run --mc-config agreement     --mc-depth 5 --mc-min-states 1500
run --mc-config common_subset --mc-depth 4 --mc-min-states 2500
run --mc-config honey_badger  --mc-depth 4 --mc-min-states 2500
run --mc-config sbv_broadcast --mc-depth 3 --mc-corrupt 1 --mc-min-states 1500
run --mc-config agreement     --mc-depth 3 --mc-corrupt 1 --mc-min-states 2000
exit "$rc"
