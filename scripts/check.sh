#!/usr/bin/env bash
# One-shot verification gate, in dependency order:
#
#   1. badgerlint — all 20 static rules over the package tree
#   2. racecheck smoke — the lockset-checker test module under
#      `pytest --racecheck` (runtime thread-safety)
#   3. wire-manifest verification — the @wire registry still matches
#      the checked-in golden manifest (serialization stability)
#   4. scenarios smoke — bad-share (the speculative-combine fallback
#      and leftover-audit attribution gate, plus both ordered-reveal
#      legs of the forged-share schedule) + ordered-reveal (ordering
#      holds at the backpressure bound under share withholding;
#      post-reveal batches bit-identical to the fault-free twin) +
#      equivocate +
#      hostile-clients (gateway attribution and twin bit-identity) +
#      geo-partition-heal and flash-crowd (WAN models over both sim
#      planes, packed co-sim byte-identical to the dict plane) +
#      crash-restart and link-flap (durable WAL recovery, the gateway
#      restart window, and TCP session-resumption replay/dedup) +
#      dark-peer-catchup and byzantine-snapshot (rejoin past the
#      replay bound via f+1 quorum state transfer; forged snapshots
#      attributed, never installed)
#   5. gateway smoke — a real-TCP serving run (n=4 validators, 2
#      tenants x 2 clients); every admitted tx committed exactly once
#      and acked, zero spurious attributions
#   6. fleet telemetry — the fleet-telemetry scenario produces trace +
#      fleet + flight artifacts from a real-TCP run under load, then
#      the post-mortem timeline CLI re-merges them under the pinned
#      scripts/fleet_slo.rules (reveal-lag p90/p99 bounds included):
#      exit non-zero on any health-rule violation or if <99% of the
#      wire-send trace contexts join to their receive on the far node
#   7. stallcheck smoke — the same fleet-telemetry scenario re-run
#      under the event-loop stall sanitizer with a pinned 0.5 s
#      budget: no callback on any serving loop may park the thread
#      (the runtime dual of the static async-blocking rule)
#   8. limbprove — the jaxpr range verifier re-proves every registered
#      crypto kernel against the pinned range_manifest.json (the
#      limb-range rule), then the exact-shadow overflow sanitizer
#      re-runs the fr device tests and the G1 product-flush
#      byte-identity plane with sampled arbitrary-precision
#      recomputation (the runtime dual of the static proof)
#   9. badgermc smoke — bounded schedule-space model checking: the
#      sbv_broadcast stack explored exhaustively to its depth bound
#      (honest n=4, zero violations, a state floor guarding against a
#      degenerate search) and the agreement stack under a Byzantine
#      node (forged/equivocating/dropped messages), both asserting
#      every safety invariant at every explored state
#
# Each stage runs even if an earlier one failed (you want the full
# report, not the first stopper), but the exit code is non-zero if ANY
# stage failed.  Under pipefail + tee the per-stage exit codes come
# from PIPESTATUS, not tee's.
#
#   scripts/check.sh              # everything
#   CHECK_LOG=/tmp/check.log scripts/check.sh
set -uo pipefail
cd "$(dirname "$0")/.."

log() {
  if [ -n "${CHECK_LOG:-}" ]; then
    tee -a "$CHECK_LOG"
  else
    cat
  fi
}

rc=0

echo "== [1/9] badgerlint (all rules) ==" | log
python -m hbbft_tpu.analysis 2>&1 | log
stage=${PIPESTATUS[0]}
[ "$stage" -ne 0 ] && rc=1

echo "== [2/9] racecheck smoke ==" | log
env JAX_PLATFORMS=cpu python -m pytest tests/test_racecheck.py -q \
  -p no:cacheprovider --racecheck 2>&1 | log
stage=${PIPESTATUS[0]}
[ "$stage" -ne 0 ] && rc=1

echo "== [3/9] wire manifest ==" | log
python -m hbbft_tpu.analysis --select wire-stability 2>&1 | log
stage=${PIPESTATUS[0]}
[ "$stage" -ne 0 ] && rc=1

echo "== [4/9] scenarios smoke ==" | log
env JAX_PLATFORMS=cpu python -m hbbft_tpu.harness.scenarios \
  --only bad-share --only ordered-reveal --only equivocate \
  --only hostile-clients \
  --only geo-partition-heal --only flash-crowd \
  --only crash-restart --only link-flap \
  --only dark-peer-catchup --only byzantine-snapshot 2>&1 | log
stage=${PIPESTATUS[0]}
[ "$stage" -ne 0 ] && rc=1

echo "== [5/9] gateway smoke ==" | log
env JAX_PLATFORMS=cpu python -m hbbft_tpu.serve.loadgen --smoke 2>&1 | log
stage=${PIPESTATUS[0]}
[ "$stage" -ne 0 ] && rc=1

echo "== [6/9] fleet telemetry (timeline + health rules) ==" | log
fleet_dir=$(mktemp -d)
env JAX_PLATFORMS=cpu HBBFT_FLEET_DIR="$fleet_dir" \
  python -m hbbft_tpu.harness.scenarios --only fleet-telemetry 2>&1 | log
stage=${PIPESTATUS[0]}
[ "$stage" -ne 0 ] && rc=1
env JAX_PLATFORMS=cpu python -m hbbft_tpu.obs.timeline \
  "$fleet_dir/trace.jsonl" "$fleet_dir/fleet.jsonl" \
  "$fleet_dir/flight.jsonl" --min-join 0.99 \
  --rules scripts/fleet_slo.rules 2>&1 | log
stage=${PIPESTATUS[0]}
[ "$stage" -ne 0 ] && rc=1
rm -rf "$fleet_dir"

echo "== [7/9] stallcheck smoke (fleet-telemetry under the sanitizer) ==" | log
env JAX_PLATFORMS=cpu python -m hbbft_tpu.harness.scenarios \
  --only fleet-telemetry --stallcheck --stall-budget 0.5 2>&1 | log
stage=${PIPESTATUS[0]}
[ "$stage" -ne 0 ] && rc=1

echo "== [8/9] limbprove (range proofs + overflow shadow smoke) ==" | log
env JAX_PLATFORMS=cpu python -m hbbft_tpu.analysis --select limb-range 2>&1 | log
stage=${PIPESTATUS[0]}
[ "$stage" -ne 0 ] && rc=1
env JAX_PLATFORMS=cpu python -m hbbft_tpu.analysis --rangecheck \
  "tests/test_fr_jax.py tests/test_mesh_flush.py::TestG1ProductByteIdentity" 2>&1 | log
stage=${PIPESTATUS[0]}
[ "$stage" -ne 0 ] && rc=1

echo "== [9/9] badgermc smoke (schedule-space model checking) ==" | log
env JAX_PLATFORMS=cpu python -m hbbft_tpu.analysis --mc \
  --mc-config sbv_broadcast --mc-depth 6 --mc-min-states 3000 2>&1 | log
stage=${PIPESTATUS[0]}
[ "$stage" -ne 0 ] && rc=1
env JAX_PLATFORMS=cpu python -m hbbft_tpu.analysis --mc \
  --mc-config agreement --mc-depth 3 --mc-corrupt 1 --mc-probes 2 \
  --mc-min-states 2000 2>&1 | log
stage=${PIPESTATUS[0]}
[ "$stage" -ne 0 ] && rc=1

if [ "$rc" -eq 0 ]; then
  echo "check: all gates clean" | log
else
  echo "check: FAILED (see stages above)" | log
fi
exit "$rc"
