#!/usr/bin/env bash
# Adversarial scenario matrix wrapper: run the full matrix (or a
# selection), forwarding all flags to the CLI.  Examples:
#   scripts/scenarios.sh
#   scripts/scenarios.sh --list
#   scripts/scenarios.sh --only fuzz --fuzz-cases 2000
#   scripts/scenarios.sh --only churn --n 64 --json
#   SCENARIO_LOG=/tmp/scenarios.log scripts/scenarios.sh
set -uo pipefail
cd "$(dirname "$0")/.."

# Under pipefail, ${PIPESTATUS[0]} is the matrix's own exit code even
# when the output is piped through tee (same idiom as lint.sh).
if [ -n "${SCENARIO_LOG:-}" ]; then
  python -m hbbft_tpu.harness.scenarios "${@+"$@"}" 2>&1 \
    | tee "$SCENARIO_LOG"
  exit "${PIPESTATUS[0]}"
fi
python -m hbbft_tpu.harness.scenarios "${@+"$@"}"
exit $?
