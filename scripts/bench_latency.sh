#!/usr/bin/env bash
# Commit-latency A-B bench: run `bench.py --latency` — the {eager,
# speculative} decryption × {serial, pipelined} epoch matrix on the
# per-node protocol stack (protocols/honey_badger.py over the
# TestNetwork scheduler, REAL BLS), plus the vectorized epoch
# driver's serial-vs-staged inter-commit gap.  The headline row is
# `commit_latency_speedup` (speculative+pipelined p50 vs the
# eager/serial verify-before-combine baseline, same seed,
# byte-identical batches) — the PR-10 acceptance gate is >= 1.5x.
#
# The third section (PR 19) is the order-then-reveal matrix: {eager,
# spec} × {inline, ordered} pipelined legs, each ordered row with its
# `acs_only_wall` floor + ratio and a `reveal_lag_p50_s` companion,
# then the `ordered_commit_vs_acs_wall` headline (the ≤1.2× gate).
#
# Examples:
#   scripts/bench_latency.sh                 # n=13 protocol net, 5 epochs
#   LAT_NODES=16 scripts/bench_latency.sh    # bigger protocol net
#   LAT_EPOCHS=8 scripts/bench_latency.sh    # more latency samples
#   LAT_REVEAL=ordered scripts/bench_latency.sh  # ordered legs only
#   LAT_OUT=latency.json scripts/bench_latency.sh  # also write a file
#
# Output: one `commit_latency_p50_s` JSON row per leg, the
# `commit_latency_speedup` headline, then two `vec_commit_gap_p50_s`
# rows.  With LAT_OUT set, all rows are collected into a JSON array.
set -uo pipefail
cd "$(dirname "$0")/.."

nodes="${LAT_NODES:-13}"
epochs="${LAT_EPOCHS:-5}"
reveal="${LAT_REVEAL:-both}"
out="${LAT_OUT:-}"

log="$(mktemp)"
trap 'rm -f "$log"' EXIT

JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python bench.py --latency \
  --k "$nodes" --epochs "$epochs" --reveal-mode "$reveal" 2>&1 | tee "$log"
rc=${PIPESTATUS[0]}

if [ -n "$out" ] && [ "$rc" = 0 ]; then
  python - "$log" "$out" <<'PY'
import json, sys

rows = []
with open(sys.argv[1]) as fh:
    for line in fh:
        line = line.strip()
        if line.startswith("{"):
            try:
                rows.append(json.loads(line))
            except ValueError:
                pass
with open(sys.argv[2], "w") as fh:
    json.dump(rows, fh, indent=2)
print("wrote %d rows to %s" % (len(rows), sys.argv[2]))
PY
fi

exit "$rc"
