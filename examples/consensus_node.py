#!/usr/bin/env python3
"""A real TCP consensus node running Reliable Broadcast.

Re-design of the reference's ``examples/consensus-node.rs`` (71 LoC +
its ``examples/network/`` transport): every process binds an address,
connects to its peers, and the node whose address sorts *first* among
all participants proposes ``--value``; every node prints the agreed
value.  Node identity is the socket address; placeholder (INSECURE)
keys are derived deterministically from the sorted address list, as in
the reference (``node.rs:105-118``).

Example — three shells:

    python examples/consensus_node.py --bind-address=127.0.0.1:5000 \
        --remote-address=127.0.0.1:5001 --remote-address=127.0.0.1:5002 \
        --value=foo
    python examples/consensus_node.py --bind-address=127.0.0.1:5001 \
        --remote-address=127.0.0.1:5000 --remote-address=127.0.0.1:5002
    python examples/consensus_node.py --bind-address=127.0.0.1:5002 \
        --remote-address=127.0.0.1:5000 --remote-address=127.0.0.1:5001
"""

import argparse
import asyncio
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from hbbft_tpu.protocols.broadcast import Broadcast
from hbbft_tpu.transport.tcp import TcpNode


async def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--bind-address", required=True, metavar="HOST:PORT")
    p.add_argument(
        "--remote-address",
        action="append",
        default=[],
        metavar="HOST:PORT",
        help="peer address (repeat once per peer)",
    )
    p.add_argument("--value", default=None, help="value to propose")
    args = p.parse_args()

    addrs = sorted(set(args.remote_address) | {args.bind_address})
    proposer = addrs[0]
    node = TcpNode(
        args.bind_address,
        args.remote_address,
        lambda ni: Broadcast(ni, proposer),
    )
    print(f"[{args.bind_address}] connecting to {len(node.peer_addrs)} peers...")
    await node.start()
    print(f"[{args.bind_address}] mesh up; proposer is {proposer}")
    if args.bind_address == proposer:
        if args.value is None:
            p.error("this node is the proposer; --value is required")
        await node.input(args.value.encode())
    outputs = await node.run(timeout=60.0)
    print(f"[{args.bind_address}] agreed value: {outputs[0]!r}")
    await node.close()


if __name__ == "__main__":
    asyncio.run(main())
