#!/usr/bin/env python3
"""Timed QueueingHoneyBadger network simulation — the reference's
headline benchmark binary (``examples/simulation.rs``), same flag
surface and per-epoch output table.

    python examples/simulation.py -n 10 -f 0 -t 1000 -b 100 \
        --lag 100 --bw 2000 --cpu 100 --tx-size 10

Add ``--real-bls`` for real BLS12-381 threshold crypto (default: fast
mock crypto, like protocol-logic tests) and ``--batched`` to route
share verifications through the fused batching façade.

``--vectorized`` switches to the array-based full-epoch co-simulation
(``harness/epoch.py``): no virtual-time network model, but it runs the
complete stack at sizes the event-driven simulator cannot reach —

    python examples/simulation.py --vectorized -n 1024 -f 50 \
        -t 4096 -b 1024
"""

import argparse
import random
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from hbbft_tpu.harness.simulation import simulate_queueing_honey_badger


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("-n", "--nodes", type=int, default=10, help="total validators")
    p.add_argument("-f", "--faulty", type=int, default=0, help="crashed (silent) nodes")
    p.add_argument("-t", "--txs", type=int, default=1000, help="transactions to process")
    p.add_argument("-b", "--batch", type=int, default=100, help="batch size (txs/epoch)")
    p.add_argument("--lag", type=float, default=100.0, help="message latency, ms")
    p.add_argument("--bw", type=float, default=2000.0, help="upstream bandwidth, kbit/s")
    p.add_argument("--cpu", type=float, default=100.0, help="CPU speed, %% of host")
    p.add_argument("--tx-size", type=int, default=10, help="transaction size, bytes")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--real-bls", action="store_true", help="real BLS12-381 crypto")
    p.add_argument("--batched", action="store_true", help="fused batched verification")
    p.add_argument(
        "--vectorized",
        action="store_true",
        help="array-based full-epoch co-simulation (north-star scale)",
    )
    p.add_argument(
        "--dynamic",
        action="store_true",
        help="with --vectorized: the full QHB = DHB + queue stack "
        "(votes/on-chain DKG/era machinery active), and one "
        "Remove-churn of the highest node id mid-run",
    )
    p.add_argument(
        "--virtual",
        action="store_true",
        help="with --vectorized: also print each epoch's SIMULATED "
        "latency under the --lag/--bw/--cpu hardware profile "
        "(the reference table's Min/MaxTime at co-simulation scale)",
    )
    p.add_argument(
        "--trace",
        metavar="PATH",
        default=None,
        help="write a JSONL observability trace to PATH "
        "(summarize with `python -m hbbft_tpu.obs.report PATH`)",
    )
    args = p.parse_args()
    if args.trace:
        from hbbft_tpu import obs

        obs.enable(args.trace)
        import atexit

        atexit.register(obs.disable)

    if 3 * args.faulty >= args.nodes:
        p.error("requires 3·f < n")
    if args.dynamic and not args.vectorized:
        p.error("--dynamic requires --vectorized")

    if args.vectorized:
        import time

        rng = random.Random(args.seed)
        hw = None
        if args.virtual:
            from hbbft_tpu.harness.simulation import HwQuality

            hw = HwQuality.from_flags(args.lag, args.bw, args.cpu)
        if args.dynamic:
            from hbbft_tpu.harness.dynamic import (
                VectorizedDynamicQueueingSim,
            )
            from hbbft_tpu.protocols.change import Complete, Remove

            qsim = VectorizedDynamicQueueingSim(
                args.nodes,
                rng,
                batch_size=args.batch,
                mock=not args.real_bls,
                verify_honest=False,
                emit_minimal=True,
                hw=hw,
            )
            f = (args.nodes - 1) // 3
            churn_target = max(qsim.validators)
            for v in qsim.validators[: f + 1]:
                qsim.vote_for(v, Remove(churn_target))
        else:
            from hbbft_tpu.harness.epoch import VectorizedQueueingSim

            qsim = VectorizedQueueingSim(
                args.nodes,
                rng,
                batch_size=args.batch,
                mock=not args.real_bls,
                verify_honest=False,
                emit_minimal=True,
                hw=hw,
            )
        qsim.input_all(
            [b"tx-%08d" % i + bytes(max(0, args.tx_size - 11)) for i in range(args.txs)]
        )
        all_ids = (
            qsim.validators
            if args.dynamic
            else sorted(qsim.sim.netinfos)
        )
        if args.dynamic and args.faulty:
            # keep the churn target (the highest id) alive: kill the
            # `faulty` ids just below it
            dead = set(all_ids[-(args.faulty + 1) : -1])
        else:
            dead = set(all_ids[-args.faulty :]) if args.faulty else set()
        committed: set = set()
        epoch = 0
        t0 = time.perf_counter()
        if args.virtual:
            print(
                f"{'Epoch':>5} {'Time':>8} {'SimTime':>9} "
                f"{'Txs':>7} {'Total':>7}"
            )
        else:
            print(f"{'Epoch':>5} {'Time':>8} {'Txs':>7} {'Total':>7}")
        while len(committed) < args.txs:
            te = time.perf_counter()
            if args.dynamic:
                # an era switch can shrink the validator set (and its
                # f bound): keep only still-current dead ids, capped at
                # the new set's tolerance
                cur = qsim.validators
                f_cap = (len(cur) - 1) // 3
                dead = set(sorted(v for v in dead if v in cur)[:f_cap])
            res = qsim.run_epoch(dead=dead)
            committed.update(res.batch.tx_iter())
            note = ""
            if args.dynamic and isinstance(res.change, Complete):
                note = f"  [era {res.era}: {res.change.change!r} complete]"
            virt = res.inner.virtual if args.dynamic else res.virtual
            sim_col = (
                f" {virt.total_s:>8.2f}s" if args.virtual and virt else ""
            )
            print(
                f"{epoch:>5} {time.perf_counter() - te:>7.2f}s{sim_col} "
                f"{len(res.batch):>7} {len(committed):>7}{note}"
            )
            epoch += 1
        wall = time.perf_counter() - t0
        print(
            f"\n{epoch} epochs | wall {wall:.2f}s "
            f"({epoch / wall:.2f} epochs/s, {len(committed) / wall:.0f} distinct tx/s)"
        )
        return

    ops = None
    if args.batched:
        from hbbft_tpu.harness.batching import BatchingBackend

        ops = BatchingBackend()

    stats, wall, sim_time = simulate_queueing_honey_badger(
        num_nodes=args.nodes,
        num_dead=args.faulty,
        num_txs=args.txs,
        batch_size=args.batch,
        tx_size=args.tx_size,
        lag_ms=args.lag,
        bw_kbit_s=args.bw,
        cpu_pct=args.cpu,
        rng=random.Random(args.seed),
        mock_crypto=not args.real_bls,
        ops=ops,
        verbose=True,
    )
    print(
        f"\n{len(stats.rows)} epochs | wall {wall:.2f}s "
        f"({len(stats.rows) / wall:.2f} epochs/s) | simulated {sim_time:.2f}s"
    )


if __name__ == "__main__":
    main()
